# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Graph subsystem differential suite (legate_sparse_tpu.graph).

Runs on the virtual 8-device CPU mesh (conftest).  Distributed
BFS / SSSP / connected-components / PageRank are checked against their
scipy.sparse.csgraph twins (PageRank against a dense numpy power
iteration) on BOTH distributed layouts, and the comm ledger deltas are
compared against the static ``semiring_spmv_comm_volumes`` prediction.
The plus-times semiring kernels are pinned bitwise against their
specialized siblings — the autotuner races them under that pair, so
the verdicts must transfer.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as scsg

import jax
import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu import graph, obs
from legate_sparse_tpu.graph import (
    MIN_PLUS, OR_AND, PLUS_TIMES, SEMIRINGS, resolve,
)
from legate_sparse_tpu.obs import counters, trace
from legate_sparse_tpu.ops import spmv as spv
from legate_sparse_tpu.parallel import shard_csr
from legate_sparse_tpu.parallel.dist_csr import (
    dist_spmv, semiring_spmv_comm_volumes, shard_vector,
)

R = len(jax.devices())
needs_mesh = pytest.mark.skipif(R < 2, reason="needs a multi-device mesh")

LAYOUTS = ("1d-row", "2d-block")


@pytest.fixture(autouse=True)
def _obs_isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was:
        trace.enable()


def _graph_csr(n=64, density=0.06, seed=0):
    rng = np.random.default_rng(seed)
    S = sp.random(n, n, density=density, random_state=rng)
    S.data[:] = rng.uniform(0.5, 2.0, S.data.shape)
    return S.tocsr()


# ----------------------------------------------------------- catalog --
def test_semiring_catalog():
    assert set(SEMIRINGS) == {"plus-times", "min-plus", "max-times",
                              "or-and"}
    assert resolve("min-plus") is MIN_PLUS
    assert resolve(OR_AND) is OR_AND
    with pytest.raises(ValueError, match="plus-times"):
        resolve("tropical")
    f32 = np.dtype(np.float32)
    assert PLUS_TIMES.identity(f32) == 0.0
    assert MIN_PLUS.identity(f32) == np.inf
    assert SEMIRINGS["max-times"].identity(f32) == -np.inf
    assert bool(OR_AND.identity(np.dtype(bool))) is False
    # additive identity == multiplicative annihilator, all entries
    for sr in SEMIRINGS.values():
        assert sr.annihilator(f32) == sr.identity(f32)


# ----------------------------------------------- kernels (1 device) --
def test_semiring_kernels_plus_times_bitwise():
    # Under plus-times every semiring kernel must be bit-identical to
    # its specialized sibling — the autotuner transfers its verdicts
    # on that basis (autotune/registry.py).
    A = sparse.csr_array(_graph_csr(96, 0.08, 3))
    x = jnp.asarray(np.random.default_rng(1).uniform(0, 1, 96).astype(
        np.asarray(A.data).dtype))
    rid = A._get_row_ids()
    nnz = jnp.asarray(A.data.shape[0], dtype=jnp.int32)
    ref = spv.csr_spmv_rowids(A.data, A.indices, rid, x, A.shape[0])
    got = spv.csr_semiring_spmv_rowids_masked(
        A.data, A.indices, rid, nnz, x, A.shape[0], "sum", "times")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    ell = A._get_ell()
    if ell is not None:
        ref_e = spv.ell_spmv(ell[0], ell[1], ell[2], x)
        got_e = spv.ell_semiring_spmv(ell[0], ell[1], ell[2], x,
                                      "sum", "times")
        np.testing.assert_array_equal(np.asarray(got_e),
                                      np.asarray(ref_e))


@pytest.mark.slow
def test_semiring_kernels_differential_dense():
    # min-plus / max-times / or-and vs dense references over the
    # STORED structure (stored zeros are edges), incl. empty rows.
    Sc = _graph_csr(72, 0.07, 5)
    A = sparse.csr_array(Sc)
    dense = Sc.toarray()
    mask = np.zeros_like(dense, dtype=bool)
    mask[Sc.nonzero()] = True
    rng = np.random.default_rng(2)
    x = rng.uniform(0, 1, 72).astype(np.asarray(A.data).dtype)
    ref_mp = np.where(mask, dense + x[None, :], np.inf).min(axis=1)
    ref_mt = np.where(mask, dense * x[None, :], -np.inf).max(axis=1)
    f = x > 0.5
    ref_oa = (mask & f[None, :]).any(axis=1)
    got_mp = graph.matvec(A, jnp.asarray(x), semiring="min-plus")
    got_mt = graph.matvec(A, jnp.asarray(x), semiring="max-times")
    got_oa = graph.matvec(A, jnp.asarray(f), semiring="or-and")
    np.testing.assert_allclose(np.asarray(got_mp), ref_mp)
    np.testing.assert_allclose(np.asarray(got_mt), ref_mt)
    assert got_oa.dtype == jnp.bool_.dtype
    np.testing.assert_array_equal(np.asarray(got_oa), ref_oa)
    # explicit kernel routing by registry label
    for label in ("semiring-csr", "semiring-ell", "semiring-sliced-ell"):
        if label == "semiring-ell" and A._get_ell() is None:
            continue
        if (label == "semiring-sliced-ell"
                and A._get_sliced_ell() is None):
            continue
        got = graph.matvec(A, jnp.asarray(x), semiring="min-plus",
                           kernel=label)
        np.testing.assert_allclose(np.asarray(got), ref_mp, rtol=1e-6)
    with pytest.raises(ValueError):
        graph.matvec(A, jnp.asarray(x), kernel="no-such-kernel")


# ------------------------------------------------- distributed SpMV --
@needs_mesh
@pytest.mark.parametrize("layout", LAYOUTS)
def test_dist_semiring_spmv_differential(layout):
    Sc = _graph_csr(64, 0.08, 7)
    A = sparse.csr_array(Sc)
    dense = Sc.toarray()
    mask = np.zeros_like(dense, dtype=bool)
    mask[Sc.nonzero()] = True
    rng = np.random.default_rng(3)
    x = rng.uniform(0, 1, 64).astype(np.asarray(A.data).dtype)
    dA = shard_csr(A, layout=layout)
    dx = shard_vector(jnp.asarray(x), dA.mesh, dA.rows_padded,
                      layout=dA.layout)
    y = np.asarray(dist_spmv(dA, dx, semiring="min-plus"))[:64]
    ref = np.where(mask, dense + x[None, :], np.inf).min(axis=1)
    np.testing.assert_allclose(y, ref)
    f = x > 0.5
    df = shard_vector(jnp.asarray(f), dA.mesh, dA.rows_padded,
                      layout=dA.layout)
    yb = np.asarray(dist_spmv(dA, df, semiring="or-and"))[:64]
    np.testing.assert_array_equal(yb, (mask & f[None, :]).any(axis=1))


@needs_mesh
@pytest.mark.parametrize("layout", LAYOUTS)
def test_dist_semiring_comm_counters_match_prediction(layout):
    # Ledger delta over K calls must match the static per-call
    # prediction within 1% (acceptance criterion; equality expected —
    # both sides are models, but the counter path goes through
    # record/merge plumbing the prediction does not).
    A = sparse.csr_array(_graph_csr(64, 0.08, 11))
    dA = shard_csr(A, layout=layout)
    x = jnp.asarray(np.linspace(0, 1, 64).astype(
        np.asarray(A.data).dtype))
    dx = shard_vector(x, dA.mesh, dA.rows_padded, layout=dA.layout)
    item = np.asarray(A.data).dtype.itemsize
    vols = semiring_spmv_comm_volumes(dA, item, item, "pmin")
    assert vols, "expected at least one collective on a multi-shard mesh"
    if layout == "2d-block":
        assert "pmin" in vols  # the semiring add all-reduce is priced
    obs.reset_all()
    K = 3
    for _ in range(K):
        dist_spmv(dA, dx, semiring="min-plus").block_until_ready()
    snap = counters.snapshot()
    for kind, nbytes in vols.items():
        got = snap.get(f"comm.dist_spmv.{kind}_bytes", 0)
        assert abs(got - K * nbytes) <= 0.01 * K * nbytes, (
            kind, got, K * nbytes)
        assert snap.get(f"comm.dist_spmv.{kind}") == K, kind
    assert snap.get("graph.dist_spmv.min-plus") == K


# -------------------------------------------------------- algorithms --
@needs_mesh
@pytest.mark.parametrize("layout", LAYOUTS)
def test_bfs_matches_scipy(layout):
    Sc = _graph_csr(64, 0.05, 21)
    A = sparse.csr_array(Sc)
    lv = graph.bfs(A, 0, layout=layout)
    order, preds = scsg.breadth_first_order(
        Sc, 0, directed=True, return_predecessors=True)
    ref = np.full(64, -1)
    ref[0] = 0
    for v in order[1:]:
        ref[v] = ref[preds[v]] + 1
    np.testing.assert_array_equal(lv, ref)


@needs_mesh
@pytest.mark.parametrize("layout", LAYOUTS)
def test_sssp_matches_dijkstra(layout):
    Sc = _graph_csr(64, 0.06, 23)
    A = sparse.csr_array(Sc)
    d = graph.sssp(A, 2, layout=layout)
    np.testing.assert_allclose(
        d, scsg.dijkstra(Sc, directed=True, indices=2), rtol=1e-6)


@needs_mesh
@pytest.mark.parametrize("layout", LAYOUTS)
def test_connected_components_matches_scipy(layout):
    # Disconnected graph: two random blocks + isolated vertices.
    rng = np.random.default_rng(31)
    B1 = sp.random(20, 20, density=0.15, random_state=rng)
    B2 = sp.random(30, 30, density=0.12, random_state=rng)
    Sc = sp.block_diag([B1, B2, sp.csr_array((14, 14))]).tocsr()
    A = sparse.csr_array(Sc)
    nc, lab = graph.connected_components(A, layout=layout)
    rnc, rlab = scsg.connected_components(Sc, directed=False)
    assert nc == rnc
    # identical partitions: the label pairing must be a bijection
    assert len(set(zip(lab.tolist(), rlab.tolist()))) == nc


@needs_mesh
@pytest.mark.parametrize("layout", LAYOUTS)
def test_pagerank_matches_dense_numpy(layout):
    Sc = _graph_csr(48, 0.08, 41)
    A = sparse.csr_array(Sc)
    pr = graph.pagerank(A, layout=layout, tol=1e-12, max_iters=200)
    n = 48
    M = np.zeros((n, n))
    outdeg = np.asarray(Sc.astype(bool).sum(axis=1)).ravel()
    for i, j in zip(*Sc.nonzero()):
        M[j, i] = 1.0 / outdeg[i]
    dang = (outdeg == 0).astype(float)
    r = np.full(n, 1.0 / n)
    for _ in range(200):
        r = 0.85 * (M @ r + (dang @ r) / n) + 0.15 / n
    np.testing.assert_allclose(pr, r, atol=1e-8)
    np.testing.assert_allclose(pr.sum(), 1.0, atol=1e-6)


@needs_mesh
def test_pagerank_multigraph_edges_conserve_mass():
    """A duplicated edge list (raw R-MAT COO semantics) must not
    inflate the degree count: M dedupes per coordinate, so outdeg has
    to dedupe too or column sums fall below 1 and rank mass leaks.
    Rank over a multigraph == rank over its simple graph, sum == 1."""
    from legate_sparse_tpu import gallery

    A = gallery.rmat(6, nnz_per_row=4,
                     rng=np.random.default_rng(7), directed=True)
    pr = graph.pagerank(A, tol=1e-12, max_iters=300)
    np.testing.assert_allclose(pr.sum(), 1.0, atol=1e-6)
    Sc = A.toscipy().tocsr().copy()  # canonicalizes: duplicates merge
    Sc.sum_duplicates()
    pr_simple = graph.pagerank(sparse.csr_array(Sc), tol=1e-12,
                               max_iters=300)
    np.testing.assert_allclose(pr, pr_simple, atol=1e-8)


@needs_mesh
def test_batched_multi_source_matches_per_source():
    Sc = _graph_csr(64, 0.05, 51)
    A = sparse.csr_array(Sc)
    srcs = [0, 7, 13]
    lvb = graph.bfs(A, srcs, layout="1d-row")
    assert lvb.shape == (3, 64)
    for i, s in enumerate(srcs):
        np.testing.assert_array_equal(lvb[i],
                                      graph.bfs(A, s, layout="1d-row"))
    db = graph.sssp(A, srcs[:2], layout="1d-row")
    for i, s in enumerate(srcs[:2]):
        np.testing.assert_allclose(
            db[i], scsg.dijkstra(Sc, directed=True, indices=s),
            rtol=1e-6)


@needs_mesh
def test_algorithm_comm_counters_match_prediction():
    # End-to-end: BFS's ledger delta == (iters + 1) x the static
    # per-sweep prediction, within 1% (the +1 is the terminating sweep
    # that finds no new vertex).
    Sc = _graph_csr(64, 0.05, 61)
    A = sparse.csr_array(Sc)
    obs.reset_all()
    graph.bfs(A, 0, layout="2d-block")
    snap = counters.snapshot()
    calls = snap.get("graph.dist_spmv.or-and")
    assert calls == snap.get("graph.bfs.iters") + 1
    # Rebuild the operator's DistCSR the same way bfs did to price it.
    from legate_sparse_tpu.graph.algorithms import _push_operator
    op, _n = _push_operator(A, directed=True, unweighted=True)
    dA = shard_csr(op, layout="2d-block")
    vols = semiring_spmv_comm_volumes(dA, 1, 1, "por")
    for kind, nbytes in vols.items():
        got = snap.get(f"comm.dist_spmv.{kind}_bytes", 0)
        want = calls * nbytes
        assert abs(got - want) <= 0.01 * want, (kind, got, want)


def test_graph_counters_and_knobs():
    from legate_sparse_tpu.settings import settings

    assert settings.graph_conv_iters >= 1
    Sc = _graph_csr(40, 0.08, 71)
    A = sparse.csr_array(Sc)
    obs.reset_all()
    pr5 = graph.pagerank(A, tol=0.0, max_iters=10, conv_test_iters=5)
    snap = counters.snapshot()
    # tol=0 never converges -> exactly max_iters device iterations,
    # quantized by the cadence (10 = 2 cycles of 5).
    assert snap.get("graph.pagerank.iters") == 10
    assert snap.get("graph.pagerank.runs") == 1
    pr2 = graph.pagerank(A, tol=0.0, max_iters=10, conv_test_iters=2)
    np.testing.assert_allclose(pr5, pr2, rtol=1e-12)


def test_sssp_negative_cycle_raises():
    D = np.zeros((4, 4))
    D[0, 1] = 1.0
    D[1, 2] = -2.0
    D[2, 1] = -2.0
    D[2, 3] = 1.0
    A = sparse.csr_array(sp.csr_array(D))
    with pytest.raises(Exception, match="[Nn]egative"):
        graph.sssp(A, 0)
