# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""2-D grid mesh: factor_grid, make_grid_mesh, column-parallel SpMM.

The reference maps 1-D launches onto 2-D process grids via projection
functors (``projections.cc:23-64``) with ``factor_int`` grid
factorization (``legate_sparse/utils.py:118-124``); here the analog is
a ("rows", "cols") mesh where the sparse matrix row-shards and dense
SpMM operands column-shard.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import (
    dist_spmm, dist_spmv, factor_grid, make_grid_mesh, make_row_mesh,
    shard_csr, shard_dense,
)
from legate_sparse_tpu.parallel.dist_csr import shard_vector


@pytest.mark.parametrize(
    "n,expect", [(8, (2, 4)), (4, (2, 2)), (6, (2, 3)), (1, (1, 1)),
                 (7, (1, 7)), (16, (4, 4))],
)
def test_factor_grid(n, expect):
    assert factor_grid(n) == expect
    r, c = factor_grid(n)
    assert r * c == n and r <= c


def _mesh_or_skip(min_dev=8):
    devs = jax.devices("cpu")
    if len(devs) < min_dev:
        pytest.skip(f"needs {min_dev} virtual devices")
    return devs


def _poisson(N, dtype=np.float32):
    n = N * N
    return sparse.diags(
        [-1.0, -1.0, 4.0, -1.0, -1.0], [-N, -1, 0, 1, N],
        shape=(n, n), format="csr", dtype=dtype,
    )


def test_grid_mesh_shape():
    devs = _mesh_or_skip(8)
    mesh = make_grid_mesh(devs[:8])
    assert dict(mesh.shape) == {"rows": 2, "cols": 4}
    mesh2 = make_grid_mesh(devs[:8], shape=(4, 2))
    assert dict(mesh2.shape) == {"rows": 4, "cols": 2}
    with pytest.raises(ValueError):
        make_grid_mesh(devs[:8], shape=(3, 2))


def test_dist_spmv_on_grid_mesh_matches():
    """The vector path still works when A lives on a 2-D grid (sparse
    blocks replicated along the column axis)."""
    devs = _mesh_or_skip(8)
    mesh = make_grid_mesh(devs[:8])          # 2 x 4
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    x = np.linspace(-1, 1, n).astype(np.float32)
    xs = shard_vector(x, mesh, dA.rows_padded)
    y = np.asarray(dist_spmv(dA, xs))[:n]
    np.testing.assert_allclose(y, A.toscipy() @ x, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("k", [4, 7])
def test_dist_spmm_grid_matches_scipy(k):
    devs = _mesh_or_skip(8)
    mesh = make_grid_mesh(devs[:8])          # 2 x 4
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, k)).astype(np.float32)
    Xs = shard_dense(X, mesh, dA.rows_padded)
    Y = np.asarray(dist_spmm(dA, Xs))[:n, :k]
    np.testing.assert_allclose(
        Y, A.toscipy() @ X, rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("grid", [False, True])
def test_dist_spmm_banded_pallas_route(monkeypatch, grid):
    """Banded matrices route dist SpMM through the per-shard Mosaic
    band kernel over the prepack (row and 2-D grid meshes); results
    match the XLA route."""
    devs = _mesh_or_skip(8)
    from legate_sparse_tpu.parallel import make_row_mesh as _mrm

    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "interpret")
    mesh = make_grid_mesh(devs[:8]) if grid else _mrm(devs[:8])
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    assert dA.pdia_tile > 0
    rng = np.random.default_rng(7)
    X = rng.standard_normal((n, 4)).astype(np.float32)
    Xs = shard_dense(X, mesh, dA.rows_padded)
    Y_pl = np.asarray(dist_spmm(dA, Xs))[:n]
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIST", "0")
    Y_xla = np.asarray(dist_spmm(dA, Xs))[:n]
    np.testing.assert_allclose(Y_pl, Y_xla, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(Y_pl, A.toscipy() @ X, rtol=1e-4,
                               atol=1e-4)


def test_dist_spmm_row_mesh_matches_scipy():
    devs = _mesh_or_skip(8)
    mesh = make_row_mesh(devs[:8])
    A = _poisson(16)
    n = A.shape[0]
    dA = shard_csr(A, mesh=mesh)
    rng = np.random.default_rng(1)
    X = rng.standard_normal((n, 3)).astype(np.float32)
    Xs = shard_dense(X, mesh, dA.rows_padded)
    Y = np.asarray(dist_spmm(dA, Xs))[:n]
    np.testing.assert_allclose(
        Y, A.toscipy() @ X, rtol=1e-4, atol=1e-4
    )


@pytest.mark.slow
def test_full_dist_stack_on_grid_mesh():
    """SpGEMM, GMG hierarchy and preconditioned CG all run on a 2-D
    grid mesh (sparse blocks replicated along the column axis)."""
    devs = _mesh_or_skip(8)
    from legate_sparse_tpu.parallel import DistGMG, dist_cg, dist_spgemm

    mesh = make_grid_mesh(devs[:8])
    n = 256
    A = sparse.diags([-1.0, 4.0, -1.0], [-16, 0, 16], shape=(n, n),
                     format="csr", dtype=np.float64)
    As = sp.diags([-1.0, 4.0, -1.0], [-16, 0, 16], shape=(n, n)).tocsr()
    dA = shard_csr(A, mesh=mesh)
    C = dist_spgemm(dA, dA)
    assert abs(C.to_csr().toscipy() - As @ As).max() < 1e-12
    gmg = DistGMG(dA, levels=2)
    x, _ = dist_cg(dA, np.ones(n), M=gmg.cycle, rtol=1e-8, maxiter=200)
    assert np.linalg.norm(As @ np.asarray(x) - 1) < 1e-6


def test_dist_spmm_all_gather_and_csr_fallback():
    """Non-banded matrix over budget for ELL: padded-CSR blocks +
    all_gather realization, on the grid mesh."""
    devs = _mesh_or_skip(8)
    mesh = make_grid_mesh(devs[:8])
    rng = np.random.default_rng(2)
    n = 128
    A_sp = sp.random(n, n, density=0.05, format="csr", random_state=rng,
                     dtype=np.float64)
    # One heavy row blows the ELL budget -> padded-CSR layout.
    heavy = sp.csr_matrix(
        (np.ones(n // 2), (np.zeros(n // 2, int),
                           np.arange(0, n, 2))), shape=(n, n),
    )
    A_sp = (A_sp + heavy).tocsr()
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh,
                   force_all_gather=True)
    assert not dA.ell or dA.halo == -1
    X = rng.standard_normal((n, 5))
    Xs = shard_dense(X, mesh, dA.rows_padded)
    Y = np.asarray(dist_spmm(dA, Xs))[:n, :5]
    np.testing.assert_allclose(Y, A_sp @ X, rtol=1e-9, atol=1e-9)
