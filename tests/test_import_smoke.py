# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Import smoke test in a pristine subprocess.

The r5 seed shipped a top-level ``from jax import shard_map`` that
fails on the installed jax and — because ``tests/conftest.py`` imports
the package — zeroed out collection of the ENTIRE suite.  This test
pins the contract that a bare ``import legate_sparse_tpu`` under
``JAX_PLATFORMS=cpu`` always works, in a subprocess so no previously
imported module can mask a broken import chain, and enumerates every
package module so a bad import in a leaf (e.g. one ``parallel``
module) can never again hide behind lazy imports."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # A pristine import must not depend on the test session's settings.
    env.pop("LEGATE_SPARSE_TPU_OBS", None)
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, cwd=_REPO, env=env,
    )


def test_package_imports_under_cpu_pin():
    r = _run("import legate_sparse_tpu; print('ok')")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ok" in r.stdout


def test_all_package_modules_import():
    code = (
        "import importlib, pkgutil\n"
        "import legate_sparse_tpu as pkg\n"
        "bad = []\n"
        "for m in pkgutil.walk_packages(pkg.__path__,\n"
        "                               prefix='legate_sparse_tpu.'):\n"
        "    try:\n"
        "        importlib.import_module(m.name)\n"
        "    except Exception as e:\n"
        "        bad.append(f'{m.name}: {e!r}')\n"
        "assert not bad, bad\n"
        "print('all-modules-ok')\n"
    )
    r = _run(code)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "all-modules-ok" in r.stdout


def test_shard_map_compat_resolves():
    # The compat shim must hand back a callable on every supported jax.
    from legate_sparse_tpu.parallel._compat import shard_map

    assert callable(shard_map)


@pytest.mark.slow
def test_bench_importable():
    # bench.py is the driver contract surface; a syntax/import error
    # there loses a whole evidence round.
    r = _run("import bench; print('bench-ok')")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "bench-ok" in r.stdout
