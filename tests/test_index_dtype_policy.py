# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Platform index-dtype policy (VERDICT r3 #4).

Under the no-x64 TPU policy, an explicit int64 device-dtype request is
silently truncated to int32 with a UserWarning — the r3 on-chip capture
showed exactly that from the indptr builds.  Every device-side
index/nnz request now routes through ``types.index_dtype()`` /
``coord_dtype_for`` (the analog of the reference's
``src/sparse/util/dispatch.h:56-77`` index-type dispatch), so a no-x64
process never asks for a width it cannot have, and >2^31 extents fail
loudly instead of wrapping.
"""

import subprocess
import sys

import numpy as np
import pytest

import legate_sparse_tpu as sparse
from legate_sparse_tpu import types


def test_coord_dtype_promotion_past_int32():
    # x64 is on in the CPU test lane: promotion must hand out int64.
    assert types.coord_dtype_for(100) == np.dtype(np.int32)
    assert types.coord_dtype_for(2**31 - 1) == np.dtype(np.int32)
    assert types.coord_dtype_for(2**31) == np.dtype(np.int64)
    assert types.coord_dtype_for(2**40) == np.dtype(np.int64)


def test_huge_shape_ctor_uses_wide_coords():
    # Shape-only ctor past 2^31 rows: no giant allocation (nnz=0), but
    # the coordinate dtype must be the wide type (synthetic shape — the
    # SURVEY hard-part-5 promotion story).
    A = sparse.csr_array((3, 2**31 + 2))
    assert np.dtype(A.indices.dtype) == np.dtype(np.int64)


_NO_X64_SNIPPET = r"""
import warnings
import numpy as np
from legate_sparse_tpu._platform import pin_cpu
pin_cpu(1)
import jax
jax.config.update("jax_enable_x64", False)   # the TPU-process policy
import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg
from legate_sparse_tpu import types

with warnings.catch_warnings():
    # The exact silent-truncation warning the r3 on-chip capture hit.
    warnings.filterwarnings(
        "error", message=".*will be truncated to dtype int32.*")
    warnings.filterwarnings(
        "error", message=".*Explicitly requested dtype.*int64.*")
    n = 512
    A = sparse.diags(
        [np.full(n - 1, -1.0, np.float32),
         np.full(n, 2.0, np.float32),
         np.full(n - 1, -1.0, np.float32)],
        [-1, 0, 1], shape=(n, n), format="csr", dtype=np.float32)
    x = np.ones(n, np.float32)
    y = np.asarray(A @ x)                        # SpMV dispatch
    C = A @ A                                    # SpGEMM
    sol, it = linalg.cg(A, x, maxiter=50)        # solver loop counters
    B = A.tocoo().tocsr()                        # conversions
    assert np.dtype(types.index_dtype()) == np.dtype(np.int32)
    try:
        types.coord_dtype_for(2**31)
        raise SystemExit("expected OverflowError for >2^31 without x64")
    except OverflowError:
        pass
print("no-x64-clean")
"""


@pytest.mark.slow
def test_no_int64_requests_under_no_x64_process():
    r = subprocess.run([sys.executable, "-c", _NO_X64_SNIPPET],
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "no-x64-clean" in r.stdout
    # Belt and braces: the warning text must not appear even as a
    # non-raised warning on some other thread/path.
    assert "truncated to dtype int32" not in r.stderr
