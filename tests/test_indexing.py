# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""csr_array indexing differential tests vs scipy."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.fixture
def pair(rng):
    A_sp = scsp.random(25, 18, density=0.25, random_state=0,
                       format="csr", dtype=np.float64)
    return sparse.csr_array(A_sp), A_sp


def _eq(ours, theirs):
    np.testing.assert_allclose(
        ours.toscipy().toarray(), theirs.toarray()
    )


def test_single_row(pair):
    A, A_sp = pair
    _eq(A[3], A_sp[[3]])
    _eq(A[-1], A_sp[[-1]])


def test_element(pair):
    A, A_sp = pair
    for (i, j) in [(0, 0), (3, 7), (24, 17), (-1, -1)]:
        assert A[i, j] == A_sp[i % 25, j % 18]


def test_row_slices(pair):
    A, A_sp = pair
    _eq(A[2:10], A_sp[2:10])
    _eq(A[::3], A_sp[::3])
    _eq(A[10:2:-2], A_sp[10:2:-2])


def test_row_arrays(pair):
    A, A_sp = pair
    idx = np.array([5, 1, 22, 1])
    _eq(A[idx], A_sp[idx])
    m = np.zeros(25, bool); m[[2, 9, 11]] = True
    _eq(A[m], A_sp[m])


def test_col_slices(pair):
    A, A_sp = pair
    _eq(A[:, 3:12], A_sp[:, 3:12])
    _eq(A[2:8, ::2], A_sp[2:8, ::2])
    _eq(A[:, np.array([0, 17, 4])], A_sp[:, np.array([0, 17, 4])])


def test_row_and_col_combo(pair):
    A, A_sp = pair
    idx = np.array([4, 0, 19])
    _eq(A[idx, 2:15], A_sp[idx, 2:15])
    _eq(A[1:20:2, np.array([3, 3, 0])],
        A_sp[1:20:2][:, np.array([3, 3, 0])])


def test_duplicate_coordinate_element_sum():
    A = sparse.csr_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 3),
    )
    assert A[0, 1] == 3.0
    assert A[1, 2] == 0.0


def test_out_of_range_raises(pair):
    A, _ = pair
    with pytest.raises(IndexError):
        _ = A[np.array([100])]


def test_pointwise_array_pairs(pair):
    A, A_sp = pair
    rows = np.array([0, 3, 24])
    cols = np.array([2, 7, 17])
    ours = A[rows, cols]
    theirs = np.asarray(A_sp[rows, cols]).ravel()
    np.testing.assert_allclose(np.asarray(ours).ravel(), theirs)


def test_element_out_of_range_raises(pair):
    A, _ = pair
    with pytest.raises(IndexError):
        _ = A[100, 0]
    with pytest.raises(IndexError):
        _ = A[0, -100]


def test_bool_mask_length_validated(pair):
    A, _ = pair
    with pytest.raises(IndexError):
        _ = A[np.array([True, False])]
    with pytest.raises(IndexError):
        _ = A[:, np.zeros(5, bool)]
