# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Matrix-market IO tests (mirrors reference ``test_io.py``: mmread
equals scipy.io.mmread).  Fixtures are generated, not shipped."""

import numpy as np
import pytest
import scipy.io
import scipy.sparse as scsp

import legate_sparse_tpu as sparse
from utils_test.gen import random_csr


@pytest.fixture
def mtx_file(tmp_path):
    def make(mat, name="m.mtx", **kw):
        path = tmp_path / name
        scipy.io.mmwrite(str(path), mat, **kw)
        return str(path)

    return make


def test_mmread_general(mtx_file):
    s = random_csr(17, 13, 0.3, 5)
    path = mtx_file(s.tocoo())
    A = sparse.mmread(path)
    expected = scipy.io.mmread(path).todense()
    np.testing.assert_allclose(np.asarray(A.todense()), expected)


def test_mmread_symmetric(mtx_file):
    s = random_csr(11, 11, 0.4, 8)
    sym = s + s.T
    path = mtx_file(sym.tocoo(), symmetry="symmetric")
    A = sparse.mmread(path)
    np.testing.assert_allclose(
        np.asarray(A.todense()), scipy.io.mmread(path).todense()
    )


def test_mmread_pattern(mtx_file, tmp_path):
    path = tmp_path / "p.mtx"
    path.write_text(
        "%%MatrixMarket matrix coordinate pattern general\n"
        "3 4 3\n1 1\n2 3\n3 4\n"
    )
    A = sparse.mmread(str(path))
    expected = np.zeros((3, 4))
    expected[0, 0] = expected[1, 2] = expected[2, 3] = 1.0
    np.testing.assert_allclose(np.asarray(A.todense()), expected)


def test_mmwrite_roundtrip(tmp_path):
    s = random_csr(9, 9, 0.5, 2)
    A = sparse.csr_array(s)
    path = tmp_path / "out.mtx"
    sparse.mmwrite(str(path), A)
    B = sparse.mmread(str(path))
    np.testing.assert_allclose(
        np.asarray(B.todense()), np.asarray(A.todense())
    )
