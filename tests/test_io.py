# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Matrix-market IO tests (mirrors reference ``test_io.py``: mmread
equals scipy.io.mmread).  Fixtures are generated, not shipped."""

import numpy as np
import pytest
import scipy.io
import scipy.sparse as scsp

import legate_sparse_tpu as sparse
from utils_test.gen import random_csr


@pytest.fixture
def mtx_file(tmp_path):
    def make(mat, name="m.mtx", **kw):
        path = tmp_path / name
        scipy.io.mmwrite(str(path), mat, **kw)
        return str(path)

    return make


def test_mmread_general(mtx_file):
    s = random_csr(17, 13, 0.3, 5)
    path = mtx_file(s.tocoo())
    A = sparse.mmread(path)
    expected = scipy.io.mmread(path).todense()
    np.testing.assert_allclose(np.asarray(A.todense()), expected)


def test_mmread_symmetric(mtx_file):
    s = random_csr(11, 11, 0.4, 8)
    sym = s + s.T
    path = mtx_file(sym.tocoo(), symmetry="symmetric")
    A = sparse.mmread(path)
    np.testing.assert_allclose(
        np.asarray(A.todense()), scipy.io.mmread(path).todense()
    )


def test_mmread_pattern(mtx_file, tmp_path):
    path = tmp_path / "p.mtx"
    path.write_text(
        "%%MatrixMarket matrix coordinate pattern general\n"
        "3 4 3\n1 1\n2 3\n3 4\n"
    )
    A = sparse.mmread(str(path))
    expected = np.zeros((3, 4))
    expected[0, 0] = expected[1, 2] = expected[2, 3] = 1.0
    np.testing.assert_allclose(np.asarray(A.todense()), expected)


def test_mmwrite_roundtrip(tmp_path):
    s = random_csr(9, 9, 0.5, 2)
    A = sparse.csr_array(s)
    path = tmp_path / "out.mtx"
    sparse.mmwrite(str(path), A)
    B = sparse.mmread(str(path))
    np.testing.assert_allclose(
        np.asarray(B.todense()), np.asarray(A.todense())
    )


def test_native_parser_matches_fallback(tmp_path):
    """When the native library is present, its parse must equal the
    numpy fallback parse on general/symmetric/skew files."""
    from legate_sparse_tpu import io as lio
    from legate_sparse_tpu.utils_native import native_available, native_mtx_read

    if not native_available():
        pytest.skip("native library not built")
    cases = {
        "gen.mtx": (
            "%%MatrixMarket matrix coordinate real general\n"
            "3 3 3\n1 2 1.5\n2 2 -2.0\n3 1 0.25\n"
        ),
        "sym.mtx": (
            "%%MatrixMarket matrix coordinate real symmetric\n"
            "3 3 3\n1 1 2.0\n2 1 -1.0\n3 3 4.0\n"
        ),
        "skew.mtx": (
            "%%MatrixMarket matrix coordinate real skew-symmetric\n"
            "3 3 2\n2 1 5.0\n3 2 -1.5\n"
        ),
        "int.mtx": (
            "%%MatrixMarket matrix coordinate integer general\n"
            "2 2 2\n1 1 3\n2 2 -7\n"
        ),
    }
    for name, text in cases.items():
        path = tmp_path / name
        path.write_text(text)
        native = native_mtx_read(str(path))
        assert native is not None, name
        host = lio._parse_mtx_host(str(path))
        assert native[0] == host[0] and native[1] == host[1]
        # Mirrored-entry *order* differs (native interleaves, the
        # fallback appends) — the assembled matrix must be identical.
        dn = scsp.coo_matrix(
            (native[4], (native[2], native[3])), shape=(native[0], native[1])
        ).toarray()
        dh = scsp.coo_matrix(
            (host[4], (host[2], host[3])), shape=(host[0], host[1])
        ).toarray()
        np.testing.assert_array_equal(dn, dh)


def test_native_coo_to_csr_matches_device(tmp_path):
    from legate_sparse_tpu.utils_native import native_available, native_coo_to_csr

    if not native_available():
        pytest.skip("native library not built")
    rng = np.random.default_rng(4)
    nnz, rows_n = 200, 23
    r = rng.integers(0, rows_n, nnz)
    c = rng.integers(0, 31, nnz)
    v = rng.standard_normal(nnz)
    out = native_coo_to_csr(r, c, v, rows_n)
    assert out is not None
    vals, cols, indptr = out
    A = sparse.csr_array((vals, cols, indptr), shape=(rows_n, 31))
    ref = scsp.csr_matrix((v, (r, c)), shape=(rows_n, 31))
    np.testing.assert_allclose(np.asarray(A.todense()), ref.toarray())
