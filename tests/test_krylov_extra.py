# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-native MINRES / LSQR vs scipy (krylov_extra.py).

The reference's solver family is cg/gmres only (reference
``legate_sparse/linalg.py``); these extend it with the symmetric-
indefinite and least-squares solvers, differential-tested like
test_cg_solve.py.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as ssl

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


def _indefinite(n, seed=0):
    rng = np.random.default_rng(seed)
    d = rng.standard_normal(n) * 3
    A_sp = sp.diags([np.full(n - 1, 1.0), d, np.full(n - 1, 1.0)],
                    [-1, 0, 1], format="csr")
    return A_sp, sparse.csr_array(A_sp), rng.standard_normal(n)


def test_minres_symmetric_indefinite():
    A_sp, A, b = _indefinite(300)
    x, it = linalg.minres(A, b, rtol=1e-10, maxiter=3000)
    res = np.linalg.norm(A_sp @ np.asarray(x) - b) / np.linalg.norm(b)
    assert res < 1e-8
    assert int(it) <= 3000


def test_minres_shift():
    A_sp, A, b = _indefinite(200, seed=1)
    x, _ = linalg.minres(A, b, shift=0.5, rtol=1e-10, maxiter=3000)
    res = np.linalg.norm((A_sp - 0.5 * sp.eye(200)) @ np.asarray(x) - b)
    assert res / np.linalg.norm(b) < 1e-8


def test_minres_preconditioned():
    A_sp, A, b = _indefinite(300)
    d = A_sp.diagonal()
    Minv = sparse.csr_array(
        sp.diags([1.0 / (np.abs(d) + 1.0)], [0], format="csr"))
    x, _ = linalg.minres(A, b, M=Minv, rtol=1e-10, maxiter=3000)
    res = np.linalg.norm(A_sp @ np.asarray(x) - b) / np.linalg.norm(b)
    assert res < 1e-8


def test_minres_callback_falls_back():
    A_sp, A, b = _indefinite(60, seed=2)
    seen = []
    x, info = linalg.minres(A, b, rtol=1e-8, maxiter=500,
                            callback=lambda xk: seen.append(1))
    assert len(seen) > 0
    res = np.linalg.norm(A_sp @ np.asarray(x) - b) / np.linalg.norm(b)
    assert res < 1e-5


@pytest.mark.parametrize("damp", [0.0, 0.7])
def test_lsqr_overdetermined(damp):
    rng = np.random.default_rng(0)
    B_sp = (sp.random(400, 120, density=0.05, format="csr",
                      random_state=rng)
            + sp.vstack([sp.eye(120), sp.csr_matrix((280, 120))])).tocsr()
    b = rng.standard_normal(400)
    out = linalg.lsqr(sparse.csr_array(B_sp), b, damp=damp,
                      atol=1e-12, btol=1e-12, iter_lim=2000)
    ref = ssl.lsqr(B_sp, b, damp=damp, atol=1e-12, btol=1e-12,
                   iter_lim=2000)
    np.testing.assert_allclose(out[0], ref[0], rtol=1e-6, atol=1e-9)
    # r2norm agreement (residual incl. damping term).
    np.testing.assert_allclose(out[4], ref[4], rtol=1e-6)


def test_lsqr_underdetermined_and_x0():
    rng = np.random.default_rng(3)
    B_sp = sp.random(50, 150, density=0.15, format="csr",
                     random_state=rng)
    b = rng.standard_normal(50)
    out = linalg.lsqr(sparse.csr_array(B_sp), b, atol=1e-12, btol=1e-12,
                      iter_lim=500)
    # Minimum-norm least squares: residual must match scipy's.
    ref = ssl.lsqr(B_sp, b, atol=1e-12, btol=1e-12, iter_lim=500)
    np.testing.assert_allclose(
        np.linalg.norm(B_sp @ out[0] - b),
        np.linalg.norm(B_sp @ ref[0] - b), rtol=1e-6, atol=1e-9)
    # warm start accepted
    out2 = linalg.lsqr(sparse.csr_array(B_sp), b, x0=out[0],
                       atol=1e-12, btol=1e-12, iter_lim=500)
    assert out2[2] <= out[2]


def test_lsqr_istop_semantics():
    # istop must mirror scipy: 1 compatible-system, 2 least-squares,
    # 0 for b = 0, 7 at the iteration limit; var is zeros(n).
    rng = np.random.default_rng(0)
    B_sp = (sp.random(400, 120, density=0.05, format="csr",
                      random_state=rng)
            + sp.vstack([sp.eye(120), sp.csr_matrix((280, 120))])).tocsr()
    B = sparse.csr_array(B_sp)
    b = rng.standard_normal(400)
    out = linalg.lsqr(B, b, atol=1e-12, btol=1e-12, iter_lim=2000)
    assert out[1] == 2 and out[9].shape == (120,)
    bc = B_sp @ rng.standard_normal(120)
    assert linalg.lsqr(B, bc, atol=1e-10, btol=1e-10,
                       iter_lim=2000)[1] == 1
    out0 = linalg.lsqr(B, np.zeros(400))
    assert out0[1] == 0 and np.all(out0[0] == 0)
    assert linalg.lsqr(B, b, atol=1e-14, btol=1e-14, iter_lim=3)[1] == 7


def test_native_solvers_accept_scipy_sparse():
    # make_linear_operator converts scipy operands, so native solver
    # paths (not just the __getattr__ fallback) take them directly.
    rng = np.random.default_rng(4)
    n = 120
    d = rng.standard_normal(n) * 3
    A_sp = sp.diags([np.full(n - 1, 1.0), d, np.full(n - 1, 1.0)],
                    [-1, 0, 1], format="csr")
    b = rng.standard_normal(n)
    x, _ = linalg.minres(A_sp, b, rtol=1e-9, maxiter=3000)
    assert np.linalg.norm(A_sp @ np.asarray(x) - b) < 1e-7
    out = linalg.lsqr(A_sp, b, atol=1e-10, btol=1e-10)
    assert out[1] in (1, 2)
    w = linalg.eigsh(A_sp, k=2, which="LA", return_eigenvectors=False)
    assert w.shape == (2,)


def test_minres_diagnostic_kwargs_no_callback():
    # show/check route through host scipy without a user callback;
    # the iteration count must still come back.
    A_sp = sp.diags([np.full(50, 4.0)], [0], format="csr")
    b = np.ones(50)
    x, it = linalg.minres(sparse.csr_array(A_sp), b, rtol=1e-8,
                          maxiter=200, check=True)
    assert it > 0
    assert np.linalg.norm(A_sp @ np.asarray(x) - b) < 1e-5


def test_lsqr_exact_x0_istop_zero():
    rng = np.random.default_rng(5)
    B_sp = sp.random(60, 40, density=0.2, format="csr", random_state=rng)
    xs = rng.standard_normal(40)
    B = sparse.csr_array(B_sp)
    # Form b through THIS package's SpMV: the istop-0 contract is
    # "entry residual exactly zero", and only the same kernel that the
    # solver uses can reproduce bitwise-zero (scipy's matmul sums in a
    # different order).
    b = np.asarray(B @ xs)
    out = linalg.lsqr(B, b, x0=xs, atol=1e-8, btol=1e-8)
    assert out[1] == 0 and out[2] == 0


@pytest.mark.parametrize("damp", [0.0, 0.7])
def test_lsmr_matches_scipy(damp):
    rng = np.random.default_rng(0)
    B_sp = (sp.random(400, 120, density=0.05, format="csr",
                      random_state=rng)
            + sp.vstack([sp.eye(120), sp.csr_matrix((280, 120))])).tocsr()
    b = rng.standard_normal(400)
    out = linalg.lsmr(sparse.csr_array(B_sp), b, damp=damp,
                      atol=1e-12, btol=1e-12, maxiter=2000)
    ref = ssl.lsmr(B_sp, b, damp=damp, atol=1e-12, btol=1e-12,
                   maxiter=2000)
    np.testing.assert_allclose(out[0], ref[0], rtol=1e-7, atol=1e-10)
    assert out[1] == ref[1]
    np.testing.assert_allclose(out[3], ref[3], rtol=1e-6)  # normr


def test_lsmr_istop_and_edge_cases():
    rng = np.random.default_rng(1)
    B_sp = (sp.random(200, 80, density=0.08, format="csr",
                      random_state=rng)
            + sp.vstack([sp.eye(80), sp.csr_matrix((120, 80))])).tocsr()
    B = sparse.csr_array(B_sp)
    # Compatible system -> istop 1 like scipy.
    xs = rng.standard_normal(80)
    out1 = linalg.lsmr(B, B_sp @ xs, atol=1e-10, btol=1e-10,
                       maxiter=2000)
    assert out1[1] == ssl.lsmr(B_sp, B_sp @ xs, atol=1e-10, btol=1e-10,
                               maxiter=2000)[1] == 1
    # Zero rhs -> istop 0, x = 0.
    out0 = linalg.lsmr(B, np.zeros(200))
    assert out0[1] == 0 and np.all(out0[0] == 0)
    # Underdetermined: residual matches scipy.
    C_sp = sp.random(40, 120, density=0.15, format="csr",
                     random_state=rng)
    bc = rng.standard_normal(40)
    out = linalg.lsmr(sparse.csr_array(C_sp), bc, atol=1e-12,
                      btol=1e-12, maxiter=1000)
    ref = ssl.lsmr(C_sp, bc, atol=1e-12, btol=1e-12, maxiter=1000)
    np.testing.assert_allclose(
        np.linalg.norm(C_sp @ out[0] - bc),
        np.linalg.norm(C_sp @ ref[0] - bc), atol=1e-7)


def test_lsmr_conlim_istop3():
    # Ill-conditioned diagonal: scipy halts with istop=3 at the
    # condition limit; so must the native loop.
    rng = np.random.default_rng(2)
    d = np.concatenate([np.ones(50), np.full(10, 1e-9)])
    I_sp = sp.diags([d], [0], format="csr")
    b = rng.standard_normal(60)
    out = linalg.lsmr(sparse.csr_array(I_sp), b, conlim=1e8, atol=0,
                      btol=0, maxiter=500, conv_test_iters=1)
    ref = ssl.lsmr(I_sp, b, conlim=1e8, atol=0, btol=0, maxiter=500)
    assert out[1] == ref[1] == 3


def test_differentiable_solve_grad():
    # grad of <c, A^-1 b> wrt b is A^-1 c for symmetric A; the reverse
    # pass is one extra solve via lax.custom_linear_solve.
    import jax
    import jax.numpy as jnp

    N = 24
    n = N * N
    main = np.full(n, 4.0)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0
    offn = np.full(n - N, -1.0)
    A = sparse.diags([main, off1, off1, offn, offn],
                     [0, 1, -1, N, -N], shape=(n, n), format="csr",
                     dtype=np.float64)
    rng = np.random.default_rng(0)
    b = jnp.asarray(rng.standard_normal(n))
    c = jnp.asarray(rng.standard_normal(n))
    g = jax.grad(
        lambda bb: jnp.vdot(c, linalg.differentiable_solve(A, bb)))(b)
    want = np.asarray(linalg.differentiable_solve(A, c))
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-7)


def test_differentiable_solve_minres_under_jit():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    n = 150
    d = rng.standard_normal(n) * 3
    S_sp = sp.diags([np.full(n - 1, 1.0), d, np.full(n - 1, 1.0)],
                    [-1, 0, 1], format="csr")
    S = sparse.csr_array(S_sp)
    b = jnp.asarray(rng.standard_normal(n))
    f = jax.jit(lambda bb: linalg.differentiable_solve(
        S, bb, method="minres", maxiter=5000).sum())
    g = jax.grad(f)(b)
    want = np.linalg.solve(S_sp.toarray().T, np.ones(n))
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5)
    with pytest.raises(ValueError, match="supports 'cg'"):
        linalg.differentiable_solve(S, b, method="gmres")


@pytest.mark.slow
def test_lsmr_scale_invariant_stopping():
    # An additive absolute-eps term in the stopping tests would
    # mis-fire on tiny-scale data; scipy's tests are relative.
    rng = np.random.default_rng(3)
    B_sp = (sp.random(200, 80, density=0.08, format="csr",
                      random_state=rng)
            + sp.vstack([sp.eye(80), sp.csr_matrix((120, 80))])).tocsr()
    b = rng.standard_normal(200)
    out = linalg.lsmr(sparse.csr_array(1e-12 * B_sp), 1e-12 * b,
                      atol=1e-12, btol=1e-12, conlim=0, maxiter=2000)
    ref = ssl.lsmr(1e-12 * B_sp, 1e-12 * b, atol=1e-12, btol=1e-12,
                   conlim=0, maxiter=2000)
    assert out[1] == ref[1]
    np.testing.assert_allclose(out[0], ref[0], rtol=1e-6)
    # atol=btol=0: machine-precision istop 4/5, not an iteration-limit
    # burnout.
    out0 = linalg.lsmr(sparse.csr_array(B_sp), b, atol=0, btol=0,
                       maxiter=2000, conv_test_iters=1)
    assert out0[1] in (4, 5)
    # b = 0 with x0: no shortcut; same minimizer as scipy.
    x0v = rng.standard_normal(80)
    o = linalg.lsmr(sparse.csr_array(B_sp), np.zeros(200), x0=x0v,
                    atol=1e-10, btol=1e-10)
    r = ssl.lsmr(B_sp, np.zeros(200), x0=x0v, atol=1e-10, btol=1e-10)
    np.testing.assert_allclose(o[0], r[0], atol=1e-8)


def test_differentiable_solve_f32_default_tolerance():
    # The default rtol must be attainable in float32 (1e-10 stagnates).
    import jax.numpy as jnp

    N = 16
    n = N * N
    main = np.full(n, 4.0, np.float32)
    off1 = np.full(n - 1, -1.0, np.float32)
    off1[np.arange(1, N) * N - 1] = 0.0
    offn = np.full(n - N, -1.0, np.float32)
    A = sparse.diags([main, off1, off1, offn, offn],
                     [0, 1, -1, N, -N], shape=(n, n), format="csr",
                     dtype=np.float32)
    b = jnp.asarray(np.random.default_rng(0).standard_normal(n),
                    jnp.float32)
    x = linalg.differentiable_solve(A, b)
    assert float(np.linalg.norm(np.asarray(A @ x) - np.asarray(b))) \
        < 1e-3
