# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""sparselint framework tests: per-rule good/bad fixtures, the
falsifiability drill over every registered rule, suppression and
baseline semantics, CLI modes, and the tier-1 full-repo gate.

The falsifiability drill is the load-bearing test: a rule that cannot
fire on its own seeded known-bad input checks nothing (the same
own-module-excluded discipline the legacy checkers established).
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import core  # noqa: E402
from tools.lint import cli  # noqa: E402
from tools.lint.core import (  # noqa: E402
    Context, Finding, all_rules, get_rule, load_baseline, run_lint,
    suppressed_by_line, write_baseline,
)

EXPECTED_RULES = {
    "fault-sites", "kernel-registry", "knob-registry",
    "lock-discipline", "monotonic-clock", "obs-docs", "plan-contract",
    "settings-epoch", "trace-purity",
}


@pytest.fixture(scope="module")
def ctx():
    return Context()


# ------------------------------------------------------------------ #
# registry
# ------------------------------------------------------------------ #

def test_registry_is_complete():
    rules = all_rules()
    assert set(rules) == EXPECTED_RULES
    for rid, rule in rules.items():
        assert rule.id == rid
        assert rule.description, f"rule {rid} has no description"
        assert rule.severity in core.SEVERITIES
        assert rule.scope_prefixes, f"rule {rid} declares no scope"


def test_duplicate_rule_id_rejected():
    class Dup(core.Rule):
        id = "monotonic-clock"

    with pytest.raises(ValueError, match="duplicate"):
        core.register(Dup)


# ------------------------------------------------------------------ #
# falsifiability drill: every rule must fire on its known-bad input
# ------------------------------------------------------------------ #

@pytest.mark.parametrize("rule_id", sorted(EXPECTED_RULES))
def test_rule_is_falsifiable(ctx, rule_id):
    findings = get_rule(rule_id).falsifiability(ctx)
    assert findings, f"rule {rule_id} produced no finding on its " \
                     f"known-bad input — it checks nothing"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.message for f in findings)


# ------------------------------------------------------------------ #
# per-rule behavior on the fixtures
# ------------------------------------------------------------------ #

def test_trace_purity_fixture_findings(ctx):
    fixture = "tools/lint/fixtures/trace_purity_bad.py"
    findings = list(get_rule("trace-purity").check(ctx, [fixture]))
    msgs = "\n".join(f.message for f in findings)
    # The six seeded violations, across a @jax.jit def and a
    # lax.while_loop cond/body pair.
    assert "print()" in msgs
    assert "float(x)" in msgs
    assert ".item()" in msgs
    assert "bool(c)" in msgs
    assert "np.asarray()" in msgs
    assert "time.time()" in msgs
    # The host-side function must stay clean: every finding names one
    # of the traced regions.
    owners = {f.message.split(":")[0] for f in findings}
    assert owners <= {"in traced bad_jitted", "in traced cond",
                      "in traced body"}


def test_trace_purity_ignores_host_code(ctx, tmp_path):
    tmp_ctx = Context(repo=str(tmp_path))
    (tmp_path / "host.py").write_text(
        "import time\n"
        "import numpy as np\n"
        "def host(x):\n"
        "    print(float(np.asarray(x).item()), time.time())\n"
        "    return x\n")
    assert list(get_rule("trace-purity").check(tmp_ctx, ["host.py"])) \
        == []


def test_lock_discipline_fixture_findings(ctx):
    rule = get_rule("lock-discipline")
    findings = rule.falsifiability(ctx)
    # Exactly the two unlocked accesses (bad_write / bad_read); the
    # locked write and the parameter-shadowing function stay clean.
    assert sorted(f.line for f in findings) == [11, 15]
    for f in findings:
        assert "'_STATE'" in f.message
        assert "with _LOCK:" in f.message


def test_lock_discipline_locked_helper_exempt(tmp_path):
    tmp_ctx = Context(repo=str(tmp_path))
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "_lock = threading.Lock()\n"
        "_state = {}\n"
        "def _compact_locked():\n"
        "    _state.clear()\n"        # caller-holds-lock convention
        "def bad():\n"
        "    _state.clear()\n")
    reg = {"m.py": {"_lock": frozenset({"_state"})}}
    findings = list(get_rule("lock-discipline").check(
        tmp_ctx, ["m.py"], registry=reg))
    assert [f.line for f in findings] == [7]


def test_settings_epoch_fixture_findings(ctx):
    fixture = "tools/lint/fixtures/settings_epoch_bad.py"
    findings = list(get_rule("settings-epoch").check(ctx, [fixture]))
    msgs = "\n".join(f.message for f in findings)
    assert "settings.__dict__" in msgs
    assert "object.__setattr__(settings" in msgs
    assert "vars(settings)" in msgs
    assert "settings.not_a_real_knob" in msgs
    # The legitimate mutation and the epoch property read are clean.
    assert len(findings) == 4


def test_settings_epoch_stale_exemption(tmp_path):
    tmp_ctx = Context(repo=str(tmp_path))
    pkg = tmp_path / "legate_sparse_tpu"
    pkg.mkdir()
    (pkg / "settings.py").write_text(
        "class Settings:\n"
        "    _EPOCH_EXEMPT = frozenset({'real', 'ghost_attr'})\n"
        "    def __init__(self):\n"
        "        self.real = 1\n"
        "settings = Settings()\n")
    findings = list(get_rule("settings-epoch").check(
        tmp_ctx, ["legate_sparse_tpu/settings.py"]))
    assert len(findings) == 1
    assert "'ghost_attr'" in findings[0].message
    assert "stale exemption" in findings[0].message


def test_knob_registry_fixture_findings(ctx):
    fixture = "tools/lint/fixtures/knob_registry_bad.py"
    findings = list(get_rule("knob-registry").check(ctx, [fixture]))
    # Only the undocumented knob fires; LEGATE_SPARSE_TPU_OBS has a
    # README row.
    assert len(findings) == 1
    assert "LEGATE_SPARSE_TPU_ZZ_UNDOCUMENTED" in findings[0].message


def test_knob_registry_prefix_and_shorthand(ctx):
    from tools.lint.rules.knob_registry import documented
    doc = ("| `LEGATE_SPARSE_TPU_RESIL_RETRIES` | ... |\n"
           "| `_PROBE_TIMEOUT` / `_PROBE_RETRIES` | ... |\n")
    shorthands = {"_PROBE_TIMEOUT", "_PROBE_RETRIES"}
    # Prefix literal covered by a documented knob extending it.
    assert documented("LEGATE_SPARSE_TPU_RESIL_", doc, shorthands)
    assert not documented("LEGATE_SPARSE_TPU_ZZ_", doc, shorthands)
    # Shorthand suffix rows cover full names.
    assert documented("LEGATE_SPARSE_TPU_PROBE_TIMEOUT", doc,
                      shorthands)
    assert not documented("LEGATE_SPARSE_TPU_PROBE_TTL", doc,
                          shorthands)


def test_monotonic_clock_fixture_findings(ctx):
    fixture = "tools/lint/fixtures/monotonic_clock_bad.py"
    findings = list(get_rule("monotonic-clock").check(ctx, [fixture]))
    # Both time.time() calls, neither time.monotonic() call.
    assert len(findings) == 2
    assert all("time.time()" in f.message for f in findings)


def test_fault_sites_rule_clean_on_repo(ctx):
    assert list(get_rule("fault-sites").check(
        ctx, get_rule("fault-sites").scope_files(ctx))) == []


def test_kernel_registry_rule_clean_on_repo(ctx):
    assert list(get_rule("kernel-registry").check(
        ctx, get_rule("kernel-registry").scope_files(ctx))) == []


def test_obs_docs_rule_clean_on_repo(ctx):
    assert list(get_rule("obs-docs").check(
        ctx, get_rule("obs-docs").scope_files(ctx))) == []


# ------------------------------------------------------------------ #
# suppression semantics
# ------------------------------------------------------------------ #

def _tmp_pkg_ctx(tmp_path, source):
    """A throwaway repo whose package holds one module."""
    pkg = tmp_path / "legate_sparse_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(source)
    return Context(repo=str(tmp_path))


def test_inline_suppression(tmp_path):
    tmp_ctx = _tmp_pkg_ctx(
        tmp_path,
        "import time\n"
        "def f():\n"
        "    a = time.time()  # lint: disable=monotonic-clock — why\n"
        "    b = time.time()  # lint: disable=all\n"
        "    c = time.time()  # lint: disable=other-rule\n"
        "    d = time.time()\n"
        "    return a, b, c, d\n")
    res = run_lint(tmp_ctx, rule_ids=["monotonic-clock"],
                   baseline_path=None)
    assert sorted(f.line for f in res.suppressed) == [3, 4]
    assert sorted(f.line for f in res.active) == [5, 6]
    assert res.exit_code == 1


def test_suppressed_by_line_bounds(ctx):
    # Whole-program findings (line 0) and out-of-range lines are never
    # suppressed.
    f0 = Finding(rule="fault-sites", path="docs/RESILIENCE.md", line=0,
                 message="m")
    assert not suppressed_by_line(ctx, f0)
    f_oob = Finding(rule="monotonic-clock", path="README.md",
                    line=10 ** 6, message="m")
    assert not suppressed_by_line(ctx, f_oob)


# ------------------------------------------------------------------ #
# baseline semantics
# ------------------------------------------------------------------ #

def test_baseline_grandfathers_and_goes_stale(tmp_path):
    tmp_ctx = _tmp_pkg_ctx(
        tmp_path,
        "import time\n"
        "def f():\n"
        "    return time.time()\n")
    baseline_path = str(tmp_path / "baseline.json")

    res = run_lint(tmp_ctx, rule_ids=["monotonic-clock"],
                   baseline_path=None)
    assert len(res.active) == 1

    write_baseline(baseline_path, res.active)
    assert len(load_baseline(baseline_path)) == 1

    # Baselined: the finding no longer fails the run.
    res2 = run_lint(tmp_ctx, rule_ids=["monotonic-clock"],
                    baseline_path=baseline_path)
    assert res2.active == []
    assert len(res2.baselined) == 1
    assert res2.stale_baseline == []
    assert res2.exit_code == 0

    # Fix the code: the baseline entry must surface as stale.
    (tmp_path / "fixed").mkdir()
    fixed_ctx = _tmp_pkg_ctx(
        tmp_path / "fixed",
        "import time\n"
        "def f():\n"
        "    return time.monotonic()\n")
    res3 = run_lint(fixed_ctx, rule_ids=["monotonic-clock"],
                    baseline_path=baseline_path)
    assert res3.active == []
    assert res3.baselined == []
    assert len(res3.stale_baseline) == 1
    assert res3.exit_code == 0


def test_baseline_is_line_number_free(tmp_path):
    # Same finding at a different line still matches the baseline:
    # the key is (rule, path, message).
    baseline_path = str(tmp_path / "baseline.json")
    f1 = Finding(rule="r", path="p.py", line=10, message="m")
    write_baseline(baseline_path, [f1])
    entries = load_baseline(baseline_path)
    f2 = Finding(rule="r", path="p.py", line=99, message="m")
    assert entries.get(f2.baseline_key()) == 1


def test_committed_baseline_is_empty():
    # The repo starts clean: the committed baseline holds no
    # grandfathered findings (additions need a PR-visible diff here).
    assert load_baseline(core.DEFAULT_BASELINE) == {}


# ------------------------------------------------------------------ #
# selection (--changed machinery)
# ------------------------------------------------------------------ #

def test_selection_scopes_non_whole_program_rules(tmp_path):
    tmp_ctx = _tmp_pkg_ctx(
        tmp_path,
        "import time\n"
        "def f():\n"
        "    return time.time()\n")
    other = tmp_path / "legate_sparse_tpu" / "other.py"
    other.write_text("import time\n"
                     "def g():\n"
                     "    return time.time()\n")
    # Only the selected file is scanned.
    res = run_lint(tmp_ctx, selection=["legate_sparse_tpu/mod.py"],
                   rule_ids=["monotonic-clock"], baseline_path=None)
    assert {f.path for f in res.active} == {"legate_sparse_tpu/mod.py"}
    # A selection outside every rule scope runs nothing.
    res2 = run_lint(tmp_ctx, selection=["unrelated.txt"],
                    rule_ids=["monotonic-clock"], baseline_path=None)
    assert res2.rules_run == []
    assert res2.active == []


def test_selection_triggers_whole_program_rules(ctx):
    # A doc edit re-runs the knob gate over its full scope.
    res = run_lint(ctx, selection=["README.md"],
                   rule_ids=["knob-registry"], baseline_path=None)
    assert res.rules_run == ["knob-registry"]
    assert res.active == []


# ------------------------------------------------------------------ #
# CLI
# ------------------------------------------------------------------ #

def test_cli_full_scan_ok(capsys):
    rc = cli.main([])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "sparselint: OK — 0 findings" in out.out


def test_cli_json_artifact(capsys):
    rc = cli.main(["--json"])
    out = capsys.readouterr().out
    data = json.loads(out)
    assert rc == 0
    assert data["findings"] == []
    assert data["exit_code"] == 0
    assert set(data["rules_run"]) == EXPECTED_RULES
    assert data["files_scanned"]


def test_cli_list_rules(capsys):
    rc = cli.main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rid in EXPECTED_RULES:
        assert rid in out


def test_cli_unknown_rule_is_usage_error(capsys):
    rc = cli.main(["--rules", "no-such-rule"])
    assert rc == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_rule_subset(capsys):
    rc = cli.main(["--rules", "monotonic-clock,trace-purity"])
    out = capsys.readouterr()
    assert rc == 0
    assert "across 2 rule(s)" in out.out


def test_cli_changed_mode(capsys):
    # Runs against the live git worktree: must succeed whatever the
    # current diff is (the repo itself stays lint-clean).
    rc = cli.main(["--changed"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_path_selection(capsys):
    rc = cli.main(["legate_sparse_tpu/resilience"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_findings_fail_with_renders(tmp_path, capsys, monkeypatch):
    # Findings render as path:line: severity: [rule] message and flip
    # the exit code.
    pkg = tmp_path / "legate_sparse_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text("import time\n"
                                "def f():\n"
                                "    return time.time()\n")
    monkeypatch.setattr(cli, "Context",
                        lambda: Context(repo=str(tmp_path)))
    rc = cli.main(["--rules", "monotonic-clock", "--baseline", "none"])
    out = capsys.readouterr()
    assert rc == 1
    assert "legate_sparse_tpu/mod.py:3: error: [monotonic-clock]" \
        in out.out
    assert "sparselint: FAILED — 1 finding(s)" in out.err


# ------------------------------------------------------------------ #
# tier-1 gate: the whole repo stays lint-clean
# ------------------------------------------------------------------ #

def test_full_repo_scan_is_clean(ctx):
    res = run_lint(ctx)
    assert res.active == [], "unsuppressed findings:\n" + "\n".join(
        f.render() for f in res.active)
    assert res.stale_baseline == []
    assert set(res.rules_run) == EXPECTED_RULES
