# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""max/min reductions and setdiag vs scipy."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse


@pytest.fixture
def pair(rng):
    A_sp = scsp.random(20, 15, density=0.3, random_state=0,
                       format="csr", dtype=np.float64)
    A_sp.data -= 0.5  # mixed signs so implicit zeros matter
    return sparse.csr_array(A_sp), A_sp


@pytest.mark.parametrize("axis", [None, 0, 1])
@pytest.mark.parametrize("op", ["max", "min"])
def test_minmax_matches_scipy(pair, axis, op):
    A, A_sp = pair
    ours = getattr(A, op)(axis=axis)
    theirs = getattr(A_sp, op)(axis=axis)
    if axis is None:
        np.testing.assert_allclose(float(ours), theirs)
    else:
        np.testing.assert_allclose(np.asarray(ours),
                                   np.asarray(theirs.todense()).ravel())


def test_minmax_dense_row_excludes_zero():
    # A fully dense row must NOT clamp max to 0.
    A_sp = scsp.csr_array(np.array([[-1.0, -2.0], [0.0, -3.0]]))
    A_sp.eliminate_zeros()
    A = sparse.csr_array(A_sp)
    np.testing.assert_allclose(np.asarray(A.max(axis=1)),
                               np.asarray(A_sp.max(axis=1).todense()).ravel())


@pytest.mark.parametrize("k", [0, 2, -3])
def test_setdiag_overwrite_and_insert(pair, k, rng):
    A, A_sp = pair
    length = min(20 + min(k, 0), 15 - max(k, 0))
    vals = rng.standard_normal(length)
    A_sp = A_sp.copy()
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        A_sp.setdiag(vals, k=k)
    A.setdiag(vals, k=k)
    np.testing.assert_allclose(A.toscipy().toarray(), A_sp.toarray())
    # matvec still works after the structural change
    x = rng.standard_normal(15)
    np.testing.assert_allclose(np.asarray(A @ x), A_sp @ x, rtol=1e-10)


def test_setdiag_scalar_broadcast(pair):
    A, A_sp = pair
    A_sp = A_sp.copy()
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        A_sp.setdiag(7.5)
    A.setdiag(7.5)
    np.testing.assert_allclose(A.toscipy().toarray(), A_sp.toarray())


def test_setdiag_k_out_of_range(pair):
    A, _ = pair
    with pytest.raises(ValueError):
        A.setdiag(1.0, k=15)


def test_minmax_canonicalizes_duplicates():
    A = sparse.csr_array(
        (np.array([5.0, -5.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 3),
    )
    assert float(A.max()) == 0.0   # true value at (0,1) is 0.0
    B = sparse.csr_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 3),
    )
    assert float(B.max()) == 3.0


def test_full_slice_mutation_isolated(rng):
    import scipy.sparse as scsp2

    A_sp = scsp2.random(6, 6, density=0.5, random_state=0, format="csr")
    A = sparse.csr_array(A_sp)
    B = A[:]
    B.setdiag(9.0)
    np.testing.assert_allclose(A.toscipy().toarray(), A_sp.toarray())
    assert float(B.toscipy().toarray()[0, 0]) == 9.0


def test_minmax_zero_size_raises():
    A = sparse.csr_array(
        (np.zeros(0), np.zeros(0, np.int32), np.zeros(6, np.int64)),
        shape=(5, 0),
    )
    with pytest.raises(ValueError):
        A.max()
    with pytest.raises(ValueError):
        A.max(axis=1)


def test_pointwise_2d_index_arrays(pair):
    A, A_sp = pair
    rows = np.array([[0, 1], [2, 3]])
    cols = np.array([[0, 1], [2, 3]])
    ours = A[rows, cols]
    assert ours.shape == (2, 2)
    theirs = np.asarray(A_sp.todense())[rows, cols]
    np.testing.assert_allclose(np.asarray(ours), theirs)
