# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Multi-process distributed lane (VERDICT r3 #5).

Every other distributed test in this suite runs one process over a
virtual 8-device mesh — collectives never leave the XLA client.  This
lane launches REAL separate OS processes joined via
``parallel.mesh.init_distributed`` (2 ranks x 4 virtual CPU devices)
and runs dist_spmv + dist_cg to tolerance over the process-spanning
mesh, so psum/halo traffic crosses an actual process boundary through
the distributed runtime — the honest analog of the reference's
multi-rank launches (reference ``test.py:24-32``).
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("LEGATE_SPARSE_TPU_TEST_DEVICES") == "1",
    reason="ranks pin their own devices; already covered in the "
           "8-device lane (no extra coverage from rerunning)",
)

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "utils_test", "multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_ranks(grid_n: int, extra=()):
    port = _free_port()
    env = dict(os.environ)
    # The workers pin their own platform/devices; drop any test-lane
    # pins so they start from a clean slate.
    env.pop("LEGATE_SPARSE_TPU_TEST_DEVICES", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), "2", str(port),
             str(grid_n), *extra],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in range(2)
    ]
    # Drain both ranks concurrently: a sequential communicate() can
    # deadlock when the OTHER rank fills its pipe mid-collective.
    import threading

    outs = [None, None]

    def _drain(i, p):
        try:
            out, err = p.communicate(timeout=480)
            outs[i] = (p.returncode, out, err)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            outs[i] = ("timeout", out, err)

    threads = [threading.Thread(target=_drain, args=(i, p))
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for rank, (rc, out, err) in enumerate(outs):
        if rc != 0 and (
            "Multiprocess computations aren't implemented" in err
        ):
            # The installed jaxlib's CPU backend cannot EXECUTE
            # cross-process computations at all (a runtime capability
            # gap, not a package bug): the lane is untestable here.
            pytest.skip(
                "installed jaxlib CPU backend lacks multi-process "
                "computation support"
            )
        assert rc == 0, f"rank {rank} rc={rc}\n{err[-2000:]}"
        assert f"MULTIPROC-OK {rank}" in out, out[-500:]


@pytest.mark.slow
def test_two_process_dist_spmv_and_cg():
    _run_ranks(16)


@pytest.mark.slow
def test_two_process_dist_larger_shape():
    # Non-trivial per-shard rows (4096 over 8 shards): halo windows and
    # padding budgets actually engage across the process boundary.
    _run_ranks(64)


@pytest.mark.slow
def test_two_process_solver_family():
    # Galerkin R@A@P hierarchy (chained dist_spgemm) + V-cycle
    # preconditioned CG + dist_gmres + dist_minres + dist_eigsh,
    # all over the spanning mesh.
    _run_ranks(16, extra=("ext",))
