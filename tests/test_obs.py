# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Tier-1 tests for the observability subsystem (legate_sparse_tpu.obs):
span recording/nesting, counters, disabled-mode no-op contract, export
formats, per-op aggregation, and the wiring into the hot paths."""

import json

import numpy as np
import pytest

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs
from legate_sparse_tpu.obs import counters, report, trace


@pytest.fixture(autouse=True)
def _obs_isolation():
    """Each test starts disabled with empty buffers and leaves no
    residue for the rest of the suite."""
    was_enabled = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was_enabled:
        trace.enable()
    else:
        trace.disable()


def _banded(n=32, dtype=np.float32):
    return sparse.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)], [-1, 0, 1],
        shape=(n, n), format="csr", dtype=dtype,
    )


# ---------------------------------------------------------------- trace --
def test_disabled_mode_records_nothing():
    assert not trace.enabled()
    with obs.span("never", nnz=1) as sp:
        assert sp is None          # null context: no live span handle
    obs.event("never.event", detail=1)
    assert obs.records() == []


def test_disabled_span_is_shared_singleton():
    # Near-zero-overhead contract: disabled span() allocates nothing.
    a = trace.span("x", k=1)
    b = trace.span("y")
    assert a is b is trace._NULL_SPAN


def test_spans_nest_and_record_depth():
    trace.enable()
    with obs.span("outer"):
        with obs.span("inner"):
            with obs.span("innermost"):
                pass
        with obs.span("inner"):
            pass
    recs = obs.records()
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    assert [r["depth"] for r in by_name["inner"]] == [1, 1]
    assert by_name["innermost"][0]["depth"] == 2
    assert by_name["outer"][0]["depth"] == 0
    # Inner spans close before outer: buffer order is completion order.
    assert [r["name"] for r in recs] == [
        "innermost", "inner", "inner", "outer"]
    # Nested wall times are consistent.
    assert by_name["outer"][0]["dur_ns"] >= by_name["inner"][0]["dur_ns"]


def test_first_call_vs_steady_state_sequencing():
    trace.enable()
    for _ in range(3):
        with obs.span("op"):
            pass
    recs = obs.records()
    assert [r["seq"] for r in recs] == [0, 1, 2]
    assert [r["first"] for r in recs] == [True, False, False]


def test_span_set_attaches_late_attrs_and_errors_are_recorded():
    trace.enable()
    with obs.span("op", early=1) as sp:
        sp.set(late="kernel-choice")
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    recs = obs.records()
    assert recs[0]["attrs"] == {"early": 1, "late": "kernel-choice"}
    assert recs[1]["attrs"]["error"] == "ValueError"


def test_events_are_instant_records():
    trace.enable()
    obs.event("platform.probe_fail", attempt=1, rc=2)
    (r,) = obs.records()
    assert r["type"] == "event"
    assert "dur_ns" not in r
    assert r["attrs"] == {"attempt": 1, "rc": 2}


def test_span_attrs_accumulate_into_counters():
    trace.enable()
    with obs.span("op", nnz=10, bytes=100):
        pass
    with obs.span("op", nnz=5, bytes=50, flops=7):
        pass
    assert counters.get("obs.nnz_processed") == 15
    assert counters.get("obs.bytes_moved") == 150
    assert counters.get("obs.flops") == 7


# ------------------------------------------------------------- counters --
def test_counters_accumulate_and_reset():
    counters.inc("a.x")
    counters.inc("a.x", 2)
    counters.inc("a.y", 1.5)
    counters.inc("b.z")
    assert counters.get("a.x") == 3
    snap = counters.snapshot("a.")
    assert snap == {"a.x": 3, "a.y": 1.5}
    counters.reset("a.")
    assert counters.get("a.x") == 0
    assert counters.get("b.z") == 1
    counters.reset()
    assert counters.snapshot() == {}


def test_counters_live_even_when_tracing_disabled():
    assert not trace.enabled()
    A = _banded()
    _ = A @ np.ones(A.shape[0], np.float32)
    assert counters.get("op.spmv") == 1
    assert obs.records() == []      # but no trace entries


# -------------------------------------------------------------- exports --
def test_chrome_trace_export_is_valid_json(tmp_path):
    trace.enable()
    with obs.span("spmv", nnz=11, bytes=88):
        pass
    obs.event("probe.fail", rc=1)
    path = tmp_path / "out.trace.json"
    n = obs.write_chrome_trace(str(path), extra_metadata={"tag": "t"})
    assert n == 2
    doc = json.loads(path.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == 2
    x = [e for e in evs if e["ph"] == "X"][0]
    assert x["name"] == "spmv" and x["dur"] >= 0
    assert x["args"]["nnz"] == 11 and x["args"]["first_call"] is True
    i = [e for e in evs if e["ph"] == "i"][0]
    assert i["name"] == "probe.fail"
    assert doc["otherData"]["tag"] == "t"
    assert "counters" in doc["otherData"]


def test_jsonl_export_and_load_roundtrip(tmp_path):
    trace.enable()
    with obs.span("op", nnz=3):
        pass
    path = tmp_path / "out.jsonl"
    assert obs.write_jsonl(str(path)) == 1
    loaded = report.load_records(str(path))
    assert loaded[0]["name"] == "op"
    assert loaded[0]["attrs"]["nnz"] == 3


def test_load_records_reads_chrome_format_back(tmp_path):
    trace.enable()
    with obs.span("op", nnz=3, bytes=24):
        pass
    with obs.span("op"):
        pass
    path = tmp_path / "out.trace.json"
    obs.write_chrome_trace(str(path))
    loaded = report.load_records(str(path))
    spans = [r for r in loaded if r["type"] == "span"]
    assert len(spans) == 2
    assert spans[0]["first"] is True and spans[1]["first"] is False


# --------------------------------------------------------------- report --
def test_report_aggregates_first_vs_steady_and_bandwidth():
    recs = [
        {"type": "span", "name": "spmv", "ts_ns": 0, "dur_ns": int(5e6),
         "seq": 0, "first": True, "attrs": {"nnz": 10, "bytes": 1000}},
        {"type": "span", "name": "spmv", "ts_ns": 0, "dur_ns": int(1e6),
         "seq": 1, "first": False, "attrs": {"nnz": 10, "bytes": 1000}},
        {"type": "span", "name": "spmv", "ts_ns": 0, "dur_ns": int(1e6),
         "seq": 2, "first": False, "attrs": {"nnz": 10, "bytes": 1000}},
        {"type": "event", "name": "probe", "ts_ns": 0},
    ]
    agg = report.aggregate(recs)
    row = agg["spmv"]
    assert row["calls"] == 3
    assert row["first_ms"] == pytest.approx(5.0)
    assert row["steady_ms"] == pytest.approx(1.0)
    assert row["nnz"] == 30
    # steady bytes (2 calls x 1000 B) over 2 ms -> 1e-3 GB/s
    assert row["gbs"] == pytest.approx(1e-3)
    assert agg["probe"]["events"] == 1
    table = report.render_table(agg, stream_gbs=2e-3)
    assert "spmv" in table and "vs_stream" in table
    assert "0.500" in table     # 1e-3 / 2e-3 roofline fraction


# --------------------------------------------------------------- wiring --
def test_spmv_span_records_path_nnz_bytes():
    trace.enable()
    A = _banded()
    x = np.ones(A.shape[0], np.float32)
    _ = A @ x
    _ = A @ x
    spans = [r for r in obs.records() if r["name"] == "spmv"]
    assert len(spans) == 2
    at = spans[0]["attrs"]
    assert at["path"] in ("dia-xla", "dia-xla-nopad", "dia-pallas",
                          "ell", "csr-rowids", "csr", "bsr")
    assert at["nnz"] == A.nnz and at["bytes"] > 0
    assert spans[0]["first"] and not spans[1]["first"]


def test_spgemm_span_records_output_nnz():
    trace.enable()
    A = _banded()
    C = A @ A
    (sp,) = [r for r in obs.records() if r["name"] == "spgemm"]
    assert sp["attrs"]["nnz"] == C.nnz
    assert sp["attrs"]["path"] in ("dia-xla", "dia-pallas", "esc")


def test_cg_span_records_iteration_count():
    import legate_sparse_tpu.linalg as linalg

    trace.enable()
    A = _banded(64)
    b = np.ones(64, np.float32)
    x, iters = linalg.cg(A, b, rtol=1e-6, maxiter=100)
    (sp,) = [r for r in obs.records() if r["name"] == "cg"]
    assert sp["attrs"]["iters"] == int(iters) > 0
    assert sp["attrs"]["n"] == 64


def test_scipy_fallback_counter_increments():
    base = counters.get("scipy_fallback.linalg.spsolve")
    import legate_sparse_tpu.linalg as linalg

    A = _banded(16, dtype=np.float64)
    b = np.ones(16, np.float64)
    _ = linalg.spsolve(A, b)
    assert counters.get("scipy_fallback.linalg.spsolve") == base + 1


def test_jit_retrace_counter_counts_compiles_not_calls():
    from legate_sparse_tpu.ops import spmv as spmv_ops

    import jax.numpy as jnp

    data = jnp.asarray(np.ones(4, np.float32))
    idx = jnp.asarray(np.array([0, 1, 2, 3], np.int32))
    ptr = jnp.asarray(np.array([0, 1, 2, 3, 4], np.int32))
    x = jnp.ones(4, jnp.float32)
    base = counters.get("trace.csr_spmv")
    for _ in range(3):
        _ = spmv_ops.csr_spmv(data, idx, ptr, x, 4)
    got = counters.get("trace.csr_spmv") - base
    # The jit cache may already be warm from earlier tests; what can
    # never happen is one trace per call.
    assert got <= 1


def test_trace_summary_tool_renders_table(tmp_path, capsys):
    import importlib.util
    import os

    trace.enable()
    A = _banded()
    _ = A @ np.ones(A.shape[0], np.float32)
    _ = A @ np.ones(A.shape[0], np.float32)
    path = tmp_path / "t.trace.json"
    obs.write_chrome_trace(str(path))

    spec = importlib.util.spec_from_file_location(
        "trace_summary",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "trace_summary.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main([str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "spmv" in out and "steady_ms" in out

    # Empty trace -> nonzero exit (the silent-no-op guard).
    empty = tmp_path / "empty.trace.json"
    empty.write_text('{"traceEvents": []}')
    assert mod.main([str(empty)]) == 2


def test_settings_obs_property_delegates():
    from legate_sparse_tpu.settings import settings

    assert settings.obs is False
    settings.obs = True
    try:
        assert trace.enabled()
    finally:
        settings.obs = False
    assert not trace.enabled()


def test_buffer_cap_drops_and_counts(monkeypatch):
    trace.enable()
    monkeypatch.setattr(trace, "MAX_RECORDS", 2)
    for _ in range(4):
        with obs.span("op"):
            pass
    assert len(obs.records()) == 2
    assert counters.get("obs.dropped_records") == 2
