# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Tier-1 tests for the observability subsystem (legate_sparse_tpu.obs):
span recording/nesting, counters, disabled-mode no-op contract, export
formats, per-op aggregation, and the wiring into the hot paths."""

import json
import re

import numpy as np
import pytest

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs
from legate_sparse_tpu.obs import counters, latency, report, trace


@pytest.fixture(autouse=True)
def _obs_isolation():
    """Each test starts disabled with empty buffers and leaves no
    residue for the rest of the suite."""
    was_enabled = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was_enabled:
        trace.enable()
    else:
        trace.disable()


def _banded(n=32, dtype=np.float32):
    return sparse.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)], [-1, 0, 1],
        shape=(n, n), format="csr", dtype=dtype,
    )


# ---------------------------------------------------------------- trace --
def test_disabled_mode_records_nothing():
    assert not trace.enabled()
    with obs.span("never", nnz=1) as sp:
        assert sp is None          # null context: no live span handle
    obs.event("never.event", detail=1)
    assert obs.records() == []


def test_disabled_span_is_shared_singleton():
    # Near-zero-overhead contract: disabled span() allocates nothing.
    a = trace.span("x", k=1)
    b = trace.span("y")
    assert a is b is trace._NULL_SPAN


def test_spans_nest_and_record_depth():
    trace.enable()
    with obs.span("outer"):
        with obs.span("inner"):
            with obs.span("innermost"):
                pass
        with obs.span("inner"):
            pass
    recs = obs.records()
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    assert [r["depth"] for r in by_name["inner"]] == [1, 1]
    assert by_name["innermost"][0]["depth"] == 2
    assert by_name["outer"][0]["depth"] == 0
    # Inner spans close before outer: buffer order is completion order.
    assert [r["name"] for r in recs] == [
        "innermost", "inner", "inner", "outer"]
    # Nested wall times are consistent.
    assert by_name["outer"][0]["dur_ns"] >= by_name["inner"][0]["dur_ns"]


def test_first_call_vs_steady_state_sequencing():
    trace.enable()
    for _ in range(3):
        with obs.span("op"):
            pass
    recs = obs.records()
    assert [r["seq"] for r in recs] == [0, 1, 2]
    assert [r["first"] for r in recs] == [True, False, False]


def test_span_set_attaches_late_attrs_and_errors_are_recorded():
    trace.enable()
    with obs.span("op", early=1) as sp:
        sp.set(late="kernel-choice")
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    recs = obs.records()
    assert recs[0]["attrs"] == {"early": 1, "late": "kernel-choice"}
    assert recs[1]["attrs"]["error"] == "ValueError"


def test_events_are_instant_records():
    trace.enable()
    obs.event("platform.probe_fail", attempt=1, rc=2)
    (r,) = obs.records()
    assert r["type"] == "event"
    assert "dur_ns" not in r
    assert r["attrs"] == {"attempt": 1, "rc": 2}


def test_span_attrs_accumulate_into_counters():
    trace.enable()
    with obs.span("op", nnz=10, bytes=100):
        pass
    with obs.span("op", nnz=5, bytes=50, flops=7):
        pass
    assert counters.get("obs.nnz_processed") == 15
    assert counters.get("obs.bytes_moved") == 150
    assert counters.get("obs.flops") == 7


# ------------------------------------------------------------- counters --
def test_counters_accumulate_and_reset():
    counters.inc("a.x")
    counters.inc("a.x", 2)
    counters.inc("a.y", 1.5)
    counters.inc("b.z")
    assert counters.get("a.x") == 3
    snap = counters.snapshot("a.")
    assert snap == {"a.x": 3, "a.y": 1.5}
    counters.reset("a.")
    assert counters.get("a.x") == 0
    assert counters.get("b.z") == 1
    counters.reset()
    assert counters.snapshot() == {}


def test_counters_live_even_when_tracing_disabled():
    assert not trace.enabled()
    A = _banded()
    _ = A @ np.ones(A.shape[0], np.float32)
    assert counters.get("op.spmv") == 1
    assert obs.records() == []      # but no trace entries


# -------------------------------------------------------------- exports --
def test_chrome_trace_export_is_valid_json(tmp_path):
    trace.enable()
    with obs.span("spmv", nnz=11, bytes=88):
        pass
    obs.event("probe.fail", rc=1)
    path = tmp_path / "out.trace.json"
    n = obs.write_chrome_trace(str(path), extra_metadata={"tag": "t"})
    assert n == 2
    doc = json.loads(path.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == 2
    x = [e for e in evs if e["ph"] == "X"][0]
    assert x["name"] == "spmv" and x["dur"] >= 0
    assert x["args"]["nnz"] == 11 and x["args"]["first_call"] is True
    i = [e for e in evs if e["ph"] == "i"][0]
    assert i["name"] == "probe.fail"
    assert doc["otherData"]["tag"] == "t"
    assert "counters" in doc["otherData"]


def test_jsonl_export_and_load_roundtrip(tmp_path):
    trace.enable()
    with obs.span("op", nnz=3):
        pass
    path = tmp_path / "out.jsonl"
    assert obs.write_jsonl(str(path)) == 1
    loaded = report.load_records(str(path))
    assert loaded[0]["name"] == "op"
    assert loaded[0]["attrs"]["nnz"] == 3


def test_load_records_reads_chrome_format_back(tmp_path):
    trace.enable()
    with obs.span("op", nnz=3, bytes=24):
        pass
    with obs.span("op"):
        pass
    path = tmp_path / "out.trace.json"
    obs.write_chrome_trace(str(path))
    loaded = report.load_records(str(path))
    spans = [r for r in loaded if r["type"] == "span"]
    assert len(spans) == 2
    assert spans[0]["first"] is True and spans[1]["first"] is False


# --------------------------------------------------------------- report --
def test_report_aggregates_first_vs_steady_and_bandwidth():
    recs = [
        {"type": "span", "name": "spmv", "ts_ns": 0, "dur_ns": int(5e6),
         "seq": 0, "first": True, "attrs": {"nnz": 10, "bytes": 1000}},
        {"type": "span", "name": "spmv", "ts_ns": 0, "dur_ns": int(1e6),
         "seq": 1, "first": False, "attrs": {"nnz": 10, "bytes": 1000}},
        {"type": "span", "name": "spmv", "ts_ns": 0, "dur_ns": int(1e6),
         "seq": 2, "first": False, "attrs": {"nnz": 10, "bytes": 1000}},
        {"type": "event", "name": "probe", "ts_ns": 0},
    ]
    agg = report.aggregate(recs)
    row = agg["spmv"]
    assert row["calls"] == 3
    assert row["first_ms"] == pytest.approx(5.0)
    assert row["steady_ms"] == pytest.approx(1.0)
    assert row["nnz"] == 30
    # steady bytes (2 calls x 1000 B) over 2 ms -> 1e-3 GB/s
    assert row["gbs"] == pytest.approx(1e-3)
    assert agg["probe"]["events"] == 1
    table = report.render_table(agg, stream_gbs=2e-3)
    assert "spmv" in table and "vs_stream" in table
    assert "0.500" in table     # 1e-3 / 2e-3 roofline fraction


# --------------------------------------------------------------- wiring --
def test_spmv_span_records_path_nnz_bytes():
    trace.enable()
    A = _banded()
    x = np.ones(A.shape[0], np.float32)
    _ = A @ x
    _ = A @ x
    spans = [r for r in obs.records() if r["name"] == "spmv"]
    assert len(spans) == 2
    at = spans[0]["attrs"]
    assert at["path"] in ("dia-xla", "dia-xla-nopad", "dia-pallas",
                          "ell", "csr-rowids", "csr", "bsr")
    assert at["nnz"] == A.nnz and at["bytes"] > 0
    assert spans[0]["first"] and not spans[1]["first"]


def test_spgemm_span_records_output_nnz():
    trace.enable()
    A = _banded()
    C = A @ A
    (sp,) = [r for r in obs.records() if r["name"] == "spgemm"]
    assert sp["attrs"]["nnz"] == C.nnz
    assert sp["attrs"]["path"] in ("dia-xla", "dia-pallas", "esc")


def test_cg_span_records_iteration_count():
    import legate_sparse_tpu.linalg as linalg

    trace.enable()
    A = _banded(64)
    b = np.ones(64, np.float32)
    x, iters = linalg.cg(A, b, rtol=1e-6, maxiter=100)
    (sp,) = [r for r in obs.records() if r["name"] == "cg"]
    assert sp["attrs"]["iters"] == int(iters) > 0
    assert sp["attrs"]["n"] == 64


def test_scipy_fallback_counter_increments():
    base = counters.get("scipy_fallback.linalg.spsolve")
    import legate_sparse_tpu.linalg as linalg

    A = _banded(16, dtype=np.float64)
    b = np.ones(16, np.float64)
    _ = linalg.spsolve(A, b)
    assert counters.get("scipy_fallback.linalg.spsolve") == base + 1


def test_jit_retrace_counter_counts_compiles_not_calls():
    from legate_sparse_tpu.ops import spmv as spmv_ops

    import jax.numpy as jnp

    data = jnp.asarray(np.ones(4, np.float32))
    idx = jnp.asarray(np.array([0, 1, 2, 3], np.int32))
    ptr = jnp.asarray(np.array([0, 1, 2, 3, 4], np.int32))
    x = jnp.ones(4, jnp.float32)
    base = counters.get("trace.csr_spmv")
    for _ in range(3):
        _ = spmv_ops.csr_spmv(data, idx, ptr, x, 4)
    got = counters.get("trace.csr_spmv") - base
    # The jit cache may already be warm from earlier tests; what can
    # never happen is one trace per call.
    assert got <= 1


def test_trace_summary_tool_renders_table(tmp_path, capsys):
    trace.enable()
    A = _banded()
    _ = A @ np.ones(A.shape[0], np.float32)
    _ = A @ np.ones(A.shape[0], np.float32)
    path = tmp_path / "t.trace.json"
    obs.write_chrome_trace(str(path))

    mod = _tool("trace_summary")
    rc = mod.main([str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "spmv" in out and "steady_ms" in out

    # Empty trace -> nonzero exit (the silent-no-op guard).
    empty = tmp_path / "empty.trace.json"
    empty.write_text('{"traceEvents": []}')
    assert mod.main([str(empty)]) == 2


def test_settings_obs_property_delegates():
    from legate_sparse_tpu.settings import settings

    assert settings.obs is False
    settings.obs = True
    try:
        assert trace.enabled()
    finally:
        settings.obs = False
    assert not trace.enabled()


def test_buffer_cap_drops_and_counts(monkeypatch):
    trace.enable()
    monkeypatch.setattr(trace, "MAX_RECORDS", 2)
    for _ in range(4):
        with obs.span("op"):
            pass
    assert len(obs.records()) == 2
    assert counters.get("obs.dropped_records") == 2


# ----------------------------------------------------- obs v3: latency --
from utils_test.tools import load_tool as _tool


def _heavy_row_csr(n=300, seed=0):
    """Engine-eligible matrix (random columns + one heavy row defeat
    the DIA/ELL/BSR structure fast paths on every platform)."""
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    S = sp.random(n, n, density=0.03, format="csr", random_state=rng,
                  dtype=np.float32)
    S = (S + sp.eye(n, format="csr", dtype=np.float32)).tocsr()
    heavy = sp.csr_matrix(
        (np.ones(n, np.float32), (np.zeros(n, np.int64),
                                  np.arange(n))), shape=(n, n))
    S = (S + heavy).tocsr()
    return sparse.csr_array(
        (S.data.astype(np.float32), S.indices, S.indptr), shape=S.shape)


def test_dot_records_latency_histogram_per_shape_bucket():
    A = _banded(48)           # bucket n64
    x = np.ones(48, np.float32)
    latency.reset("lat.")
    for _ in range(5):
        _ = np.asarray(A @ x)
    hist = latency.get("lat.spmv.n64")
    assert hist is not None and hist.count == 5
    assert hist.quantile(0.5) > 0
    # spmm keyed by the same bucket
    _ = np.asarray(A @ np.ones((48, 3), np.float32))
    assert latency.get("lat.spmm.n64").count == 1


def test_latency_histograms_add_zero_sync_to_hot_path():
    """Acceptance pin (mirrors the resilience inertness test):
    steady-state dots with obs ON move latency histograms but leave
    every trace.* (compile) and transfer.* (host-sync) counter
    untouched — the recording is pure host-side arithmetic."""
    trace.enable()
    A = _banded(64)
    x = np.ones(64, np.float32)
    _ = np.asarray(A @ x)                  # warm compile
    latency.reset("lat.")
    before = {k: v for k, v in counters.snapshot().items()
              if k.startswith("trace.") or k.startswith("transfer.")}
    for _ in range(10):
        _ = np.asarray(A @ x)
    after = {k: v for k, v in counters.snapshot().items()
             if k.startswith("trace.") or k.startswith("transfer.")}
    assert after == before, "histogram traffic moved a sync counter"
    assert latency.get("lat.spmv.n64").count == 10


def test_solver_latency_histograms_recorded():
    A = _banded(96)
    b = np.ones(96, np.float32)
    latency.reset("lat.")
    _x, _it = sparse.linalg.cg(A, b, maxiter=10)
    assert latency.get("lat.cg.solve.n128").count == 1
    _x, _it = sparse.linalg.gmres(A, b, restart=5, maxiter=10)
    h = latency.get("lat.gmres.cycle.n128")
    assert h is not None and h.count >= 1


def test_chrome_trace_embeds_histograms_and_summary_renders(
        tmp_path, capsys):
    trace.enable()
    A = _banded()
    _ = A @ np.ones(A.shape[0], np.float32)
    path = tmp_path / "lat.trace.json"
    obs.write_chrome_trace(str(path))
    doc = json.loads(path.read_text())
    hists = doc["otherData"]["histograms"]
    ser = hists.get("lat.spmv.n32")       # _banded() is n=32
    assert ser is not None, sorted(hists)
    assert ser["count"] >= 1 and ser["buckets"]

    mod = _tool("trace_summary")
    rc = mod.main([str(path), "--latency"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "latency histograms:" in out
    assert "lat.spmv." in out and "p99" in out


# ------------------------------------------- obs v3: request lifecycle --
def test_engine_request_lifecycle_spans_and_wait_histograms():
    from legate_sparse_tpu.engine import Engine, RequestExecutor

    trace.enable()
    latency.reset("lat.engine.")
    counters.reset("engine.exec.outcome.")
    A = _heavy_row_csr()
    x = np.ones(A.shape[1], np.float32)
    ex = RequestExecutor(Engine(), max_batch=4, queue_depth=64,
                         timeout_ms=0)
    try:
        futs = [ex.submit(A, x) for _ in range(4)]   # max-batch
        extra = ex.submit(A, x)
        ex.flush()                                   # k=1 dispatch
        for f in futs + [extra]:
            _ = np.asarray(f.result(timeout=60))
    finally:
        ex.shutdown()
    recs = [r for r in obs.records() if r["name"] == "engine.request"]
    assert len(recs) == 5
    rids = [r["attrs"]["rid"] for r in recs]
    assert len(set(rids)) == 5, "request ids must be unique"
    for r in recs:
        at = r["attrs"]
        assert at["outcome"] == "resolved"
        assert at["queue_ms"] >= 0 and at["batch_ms"] >= 0
        assert at["dispatch_ms"] > 0
        assert r["dur_ns"] > 0
    ks = sorted(r["attrs"]["batch_k"] for r in recs)
    assert ks == [1, 4, 4, 4, 4]
    assert counters.get("engine.exec.outcome.resolved") == 5
    assert latency.get("lat.engine.wait.resolved").count == 5
    occ = latency.get("lat.engine.batch_occupancy")
    assert occ.count == 2 and occ.sum == pytest.approx(5.0)
    req_hists = latency.snapshot("lat.engine.request.")
    assert sum(h.count for h in req_hists.values()) == 5


def test_engine_request_inline_and_shed_record_waits():
    """Satellite pin: EVERY outcome records its wait — the inline
    (ineligible-matrix) path and the shed path, not just shed."""
    from legate_sparse_tpu.engine import Engine, RequestExecutor
    from legate_sparse_tpu.resilience import deadline as rdeadline
    from legate_sparse_tpu.settings import settings

    trace.enable()
    latency.reset("lat.engine.")
    counters.reset("engine.exec.outcome.")
    A_banded = _banded(64)            # DIA fast path -> inline service
    x = np.ones(64, np.float32)
    ex = RequestExecutor(Engine(), max_batch=4, queue_depth=64,
                         timeout_ms=0)
    try:
        f = ex.submit(A_banded, x)
        _ = np.asarray(f.result(timeout=60))
        assert counters.get("engine.exec.outcome.inline") == 1
        assert latency.get("lat.engine.wait.inline").count == 1

        A_el = _heavy_row_csr(seed=2)
        x_el = np.ones(A_el.shape[1], np.float32)
        saved = settings.resil
        try:
            settings.resil = True
            with rdeadline.scope(0.0):
                fut = ex.submit(A_el, x_el)
            out = fut.result(timeout=10)
            assert type(out).__name__ == "Rejected"
            assert out.waited_ms >= 0
        finally:
            settings.resil = saved
        assert counters.get("engine.exec.outcome.shed") == 1
        assert latency.get("lat.engine.wait.shed").count == 1
    finally:
        ex.shutdown()
    outs = {r["attrs"]["outcome"]
            for r in obs.records() if r["name"] == "engine.request"}
    assert outs == {"inline", "shed"}


# ------------------------------------------------ obs v3: OpenMetrics --
def test_openmetrics_snapshot_parses_minimal_format():
    """The exposition text must satisfy a minimal OpenMetrics parse:
    valid sample syntax, counter samples ending in _total, histogram
    bucket series cumulative with ascending le ending at +Inf ==
    _count, terminated by # EOF."""
    counters.reset("omt.")
    latency.reset("lat.omt.")
    counters.inc("omt.calls", 3)
    for v in (0.5, 1.5, 1.5, 200.0, 0.0):
        latency.observe("lat.omt.demo", v)
    text = obs.snapshot_openmetrics()
    assert text.endswith("# EOF\n")
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(?:\{([a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*)\})? '
        r'(\S+)$')
    buckets = {}
    sums = {}
    cnts = {}
    seen_counter = False
    for line in text.splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (TYPE|HELP|EOF)", line), line
            continue
        m = sample_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        metric, labels, value = m.groups()
        labels = dict(re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"',
                                 labels or ""))
        if metric == "legate_sparse_tpu_counter_total":
            seen_counter = True
            if labels.get("name") == "omt.calls":
                assert float(value) == 3
        elif metric == "legate_sparse_tpu_latency_bucket":
            buckets.setdefault(labels["name"], []).append(
                (labels["le"], float(value)))
        elif metric == "legate_sparse_tpu_latency_sum":
            sums[labels["name"]] = float(value)
        elif metric == "legate_sparse_tpu_latency_count":
            cnts[labels["name"]] = float(value)
    assert seen_counter
    assert "lat.omt.demo" in buckets
    series = buckets["lat.omt.demo"]
    assert series[-1][0] == "+Inf"
    les = [float(le) for le, _c in series[:-1]]
    assert les == sorted(les), "le boundaries must ascend"
    vals = [c for _le, c in series]
    assert vals == sorted(vals), "bucket counts must be cumulative"
    assert series[-1][1] == cnts["lat.omt.demo"] == 5
    assert sums["lat.omt.demo"] == pytest.approx(203.5)
    counters.reset("omt.")
    latency.reset("lat.omt.")


def test_write_openmetrics_to_file_and_env(tmp_path, monkeypatch):
    from legate_sparse_tpu.obs import export

    counters.inc("omt.file", 1)
    p = tmp_path / "metrics.prom"
    out = export.write_openmetrics(str(p))
    assert out == str(p)
    text = p.read_text()
    assert text.endswith("# EOF\n")
    assert 'name="omt.file"' in text
    # env-default path
    monkeypatch.setenv(export.ENV_PROM_FILE, str(tmp_path / "e.prom"))
    export.write_openmetrics()
    assert (tmp_path / "e.prom").read_text().endswith("# EOF\n")
    with monkeypatch.context() as mc:
        mc.delenv(export.ENV_PROM_FILE)
        with pytest.raises(ValueError):
            export.write_openmetrics()
    counters.reset("omt.")


# ------------------------------------------ obs v3: docs coverage gate --
def test_check_obs_docs_passes(capsys):
    rc = _tool("check_obs_docs").main([])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_check_obs_docs_catches_rot(tmp_path, capsys, monkeypatch):
    """An undocumented emission literal must fail the pass — that is
    the rot the tool exists to catch."""
    mod = _tool("check_obs_docs")
    rogue = tmp_path / "rogue.py"
    rogue.write_text('_obs.inc("zz.totally_undocumented")\n')
    monkeypatch.setattr(mod, "PKG_DIR", str(tmp_path))
    rc = mod.main([])
    out = capsys.readouterr()
    assert rc == 1
    assert "zz.totally_undocumented" in out.err
