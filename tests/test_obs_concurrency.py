# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Obs under threads: counter monotonicity, tear-free snapshots, the
per-thread buffered handles (lock-free hot path), streaming latency
histograms (exact totals, tear-free merges, quantile error bound), and
span nesting integrity while threaded distributed ops run on the
virtual mesh."""

import math
import threading
import time

import numpy as np
import pytest

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs
from legate_sparse_tpu.obs import counters, latency, trace
from legate_sparse_tpu.parallel import make_row_mesh, shard_csr
from legate_sparse_tpu.parallel.dist_csr import dist_spmv, shard_vector

R = len(jax.devices())


@pytest.fixture(autouse=True)
def _obs_isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    yield
    obs.reset_all()
    if was:
        trace.enable()
    else:
        trace.disable()


# ------------------------------------------------------------- counters --
def test_counters_monotonic_and_untorn_under_threads():
    """Writers bump x then y each round; every snapshot a concurrent
    reader takes must satisfy x >= y (no torn pair) and both values
    must be nondecreasing across successive snapshots."""
    N, M = 4, 2000
    counters.reset("cc.")
    start = threading.Barrier(N + 1)
    done = threading.Event()

    def writer():
        start.wait()
        for _ in range(M):
            counters.inc("cc.x")
            counters.inc("cc.y")

    threads = [threading.Thread(target=writer) for _ in range(N)]
    for t in threads:
        t.start()

    seen = []

    def reader():
        # Bounded, briefly-yielding sampler: an unbounded hot spin on
        # the module lock starves the writers into a convoy (and eats
        # memory) without testing anything extra.
        start.wait()
        while not done.is_set() and len(seen) < 2000:
            snap = counters.snapshot("cc.")
            seen.append((snap.get("cc.x", 0), snap.get("cc.y", 0)))
            time.sleep(0)       # yield the GIL deterministically

    rt = threading.Thread(target=reader)
    rt.start()
    for t in threads:
        t.join()
    done.set()
    rt.join()

    assert counters.get("cc.x") == counters.get("cc.y") == N * M
    prev = (0, 0)
    for x, y in seen:
        assert x >= y, "torn snapshot: y visible without its x"
        assert x >= prev[0] and y >= prev[1], "counter went backwards"
        prev = (x, y)


def test_buffered_handles_concurrent_exact_sum():
    """One lock-free handle per thread, all feeding one counter: the
    merged total must be exact — no lost increments."""
    N, M = 8, 5000
    counters.reset("cc.")
    start = threading.Barrier(N)

    def worker():
        h = counters.handle("cc.buffered")
        start.wait()
        for _ in range(M):
            h.inc()

    threads = [threading.Thread(target=worker) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counters.get("cc.buffered") == N * M
    assert counters.snapshot("cc.")["cc.buffered"] == N * M


def test_buffered_handle_reset_rebases_not_mutates():
    counters.reset("cc.")
    h = counters.handle("cc.rebase")
    h.inc(5)
    assert counters.get("cc.rebase") == 5
    counters.reset("cc.")
    assert counters.get("cc.rebase") == 0
    h.inc(2)
    assert counters.get("cc.rebase") == 2
    # Prefix reset only touches matching handles.
    h2 = counters.handle("dd.other")
    h2.inc(3)
    counters.reset("cc.")
    assert counters.get("cc.rebase") == 0
    assert counters.get("dd.other") == 3
    counters.reset("dd.")


def test_handle_and_inc_merge_into_one_counter():
    counters.reset("cc.")
    counters.inc("cc.mixed", 10)
    counters.handle("cc.mixed").inc(5)
    assert counters.get("cc.mixed") == 15
    snap = counters.snapshot()
    assert snap["cc.mixed"] == 15


def test_handle_returns_same_object_per_thread_per_name():
    h1 = counters.handle("cc.same")
    h2 = counters.handle("cc.same")
    assert h1 is h2
    got = {}

    def other():
        got["h"] = counters.handle("cc.same")

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert got["h"] is not h1           # per-thread ownership
    counters.reset("cc.")


def test_dead_thread_handles_fold_and_compact():
    """Handles owned by finished threads must fold their pending
    amounts into the base counters and leave the registry at the next
    compaction sweep — a thread-pool-per-request service must not leak
    one Handle per (thread, name) forever."""
    counters.reset("cc.")

    def short_lived():
        counters.handle("cc.dead").inc(3)

    threads = [threading.Thread(target=short_lived) for _ in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counters.get("cc.dead") == 15      # pending still visible
    with counters._lock:
        before = sum(1 for h in counters._handles
                     if h.name == "cc.dead")
        counters._compact_locked()
        after = sum(1 for h in counters._handles
                    if h.name == "cc.dead")
    assert before == 5 and after == 0
    # Folded into the base dict: totals survive the compaction.
    assert counters.get("cc.dead") == 15
    assert counters.snapshot("cc.")["cc.dead"] == 15
    counters.reset("cc.")


# ----------------------------------------------------------- histograms --
def _exact_quantile(sorted_vals, q):
    """Nearest-rank comparator matching Histogram.quantile's rank."""
    rank = max(1, min(len(sorted_vals),
                      math.ceil(q * len(sorted_vals))))
    return sorted_vals[rank - 1]


def test_histogram_concurrent_observe_exact_totals():
    """One lock-free handle per thread feeding one histogram: the
    merged count AND sum must be exact — no lost observations."""
    N, M = 8, 5000
    latency.reset("hh.")
    start = threading.Barrier(N)

    def worker(i):
        h = latency.handle("hh.total")
        start.wait()
        for k in range(M):
            h.observe(1.0 + (k % 7))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    hist = latency.get("hh.total")
    assert hist.count == N * M
    expected_sum = N * sum(1.0 + (k % 7) for k in range(M))
    assert hist.sum == pytest.approx(expected_sum, rel=1e-12)
    latency.reset("hh.")


def test_histogram_snapshots_tear_free_and_monotone_under_writers():
    """Concurrent merged snapshots must be monotone per histogram
    (counts never go backwards — the tear-free/rebase contract) and
    exact once the writers join.  NOTE: no cross-histogram ordering is
    asserted — snapshot() only promises per-histogram consistency
    (writers don't take the module lock, so a reader can observe y
    ahead of x between its two per-handle reads)."""
    N, M = 4, 3000
    latency.reset("hh.")
    start = threading.Barrier(N + 1)
    done = threading.Event()

    def writer():
        hx = latency.handle("hh.x")
        hy = latency.handle("hh.y")
        start.wait()
        for _ in range(M):
            hx.observe(2.0)
            hy.observe(2.0)

    threads = [threading.Thread(target=writer) for _ in range(N)]
    for t in threads:
        t.start()

    seen = []

    def reader():
        start.wait()
        while not done.is_set() and len(seen) < 2000:
            snap = latency.snapshot("hh.")
            seen.append((snap["hh.x"].count if "hh.x" in snap else 0,
                         snap["hh.y"].count if "hh.y" in snap else 0))
            time.sleep(0)

    rt = threading.Thread(target=reader)
    rt.start()
    for t in threads:
        t.join()
    done.set()
    rt.join()

    assert latency.get("hh.x").count == N * M
    assert latency.get("hh.y").count == N * M
    prev = (0, 0)
    for x, y in seen:
        assert x >= prev[0] and y >= prev[1], "count went backwards"
        prev = (x, y)
    latency.reset("hh.")


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_histogram_quantile_error_bound_fuzzed(dtype):
    """Quantile estimates must stay within the documented REL_ERR of
    exact nearest-rank sorted quantiles, on log-uniform fuzzed samples
    spanning the full 1..1e6 range (f32 and f64 sources)."""
    rng = np.random.default_rng(42)
    latency.reset("hh.")
    for trial in range(3):
        latency.reset("hh.fuzz")
        vals = np.exp(rng.uniform(np.log(1.0), np.log(1e6),
                                  size=4000)).astype(dtype)
        h = latency.handle("hh.fuzz")
        for v in vals:
            h.observe(float(v))
        hist = latency.get("hh.fuzz")
        assert hist.count == len(vals)
        svals = sorted(float(v) for v in vals)
        for q in (0.05, 0.5, 0.9, 0.95, 0.99, 1.0):
            est = hist.quantile(q)
            exact = _exact_quantile(svals, q)
            err = abs(est - exact) / exact
            assert err <= latency.REL_ERR * (1 + 1e-6), (
                dtype, trial, q, est, exact, err)
        # max() is an upper bound within one bucket of the true max.
        assert hist.max() >= svals[-1]
        assert hist.max() <= svals[-1] * 2 ** (1.0 / latency.SUB)
    latency.reset("hh.")


def test_histogram_reset_rebases_and_merge_adds():
    latency.reset("hh.")
    h = latency.handle("hh.rebase")
    for v in (1.0, 2.0, 4.0):
        h.observe(v)
    assert latency.get("hh.rebase").count == 3
    latency.reset("hh.")
    assert latency.get("hh.rebase").count == 0
    h.observe(8.0)
    hist = latency.get("hh.rebase")
    assert hist.count == 1
    assert hist.sum == pytest.approx(8.0)
    # merge: counts and sums add, quantiles follow the merged mass.
    latency.observe("hh.other", 8.0)
    merged = hist.merge(latency.get("hh.other"))
    assert merged.count == 2
    assert merged.sum == pytest.approx(16.0)
    assert merged.quantile(1.0) == pytest.approx(
        8.0, rel=latency.REL_ERR * (1 + 1e-6))
    latency.reset("hh.")


def test_histogram_zero_and_serialization_roundtrip():
    latency.reset("hh.")
    h = latency.handle("hh.zero")
    h.observe(0.0)
    h.observe(-1.0)          # zero bucket, contributes 0 to the sum
    h.observe(3.0)
    hist = latency.get("hh.zero")
    assert hist.count == 3
    assert hist.sum == pytest.approx(3.0)
    assert hist.quantile(0.1) == 0.0       # zero bucket reports 0.0
    rt = latency.Histogram.from_dict("hh.zero", hist.to_dict())
    assert rt.count == hist.count
    assert rt.sum == pytest.approx(hist.sum)
    assert rt.quantile(0.99) == hist.quantile(0.99)
    latency.reset("hh.")


def test_histogram_dead_thread_handles_fold_and_compact():
    """Observations from finished threads must survive compaction —
    the same leak bound as counters.Handle."""
    latency.reset("hh.")

    def short_lived():
        latency.handle("hh.dead").observe(4.0)

    threads = [threading.Thread(target=short_lived) for _ in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert latency.get("hh.dead").count == 5
    with latency._lock:
        before = sum(1 for h in latency._handles
                     if h.name == "hh.dead")
        latency._compact_locked()
        after = sum(1 for h in latency._handles if h.name == "hh.dead")
    assert before == 5 and after == 0
    hist = latency.get("hh.dead")
    assert hist.count == 5
    assert hist.sum == pytest.approx(20.0)
    latency.reset("hh.")


# ---------------------------------------------------------------- spans --
def test_span_nesting_integrity_across_threads():
    """The depth stack is thread-local: concurrent nesting in N
    threads must record exact depths with no cross-thread leakage."""
    trace.enable()
    N, M = 4, 200
    start = threading.Barrier(N)

    def worker(i):
        start.wait()
        for _ in range(M):
            with obs.span(f"thr{i}.outer"):
                with obs.span(f"thr{i}.inner"):
                    pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = obs.records()
    for i in range(N):
        outer = [r for r in recs if r["name"] == f"thr{i}.outer"]
        inner = [r for r in recs if r["name"] == f"thr{i}.inner"]
        assert len(outer) == len(inner) == M
        assert all(r["depth"] == 0 for r in outer)
        assert all(r["depth"] == 1 for r in inner)
        # seq is globally consistent per name: 0..M-1 exactly once.
        assert sorted(r["seq"] for r in outer) == list(range(M))
        assert sum(1 for r in outer if r["first"]) == 1


# ---------------------------------------------------- threaded dist ops --
@pytest.mark.skipif(R < 2, reason="needs a multi-device mesh")
def test_threaded_dist_spmv_ledger_consistent():
    """dist_spmv dispatched from several threads while span/counter
    hammer threads and a snapshotting observer run concurrently: the
    op and comm counters must account every call exactly, spans must
    all be recorded with intact nesting, and no snapshot may observe
    bytes ahead of the matching call count.

    NOTE the ``launch`` lock: concurrent launches of COLLECTIVE
    programs (ppermute/all_gather) on a multi-device mesh deadlock in
    the XLA CPU backend — device programs from different launches
    interleave and the collective rendezvous never completes (a
    backend property, reproducible with a bare jitted shard_map
    ppermute from two threads; real meshes order launches through a
    single dispatch path).  The obs layer itself has no such
    constraint, which is exactly what the unserialized hammer threads
    exercise alongside."""
    trace.enable()
    mesh = make_row_mesh()
    n = 32 * R
    A = sparse.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)], [-1, 0, 1],
        shape=(n, n), format="csr", dtype=np.float32,
    )
    dA = shard_csr(A, mesh=mesh)
    x = shard_vector(np.ones(n, np.float32), mesh, dA.rows_padded)
    _ = np.asarray(dist_spmv(dA, x))    # compile before the storm
    trace.reset()                       # drop the warm-up span
    counters.reset("comm.")
    counters.reset("op.dist_spmv")

    N, M, H = 4, 8, 2
    launch = threading.Lock()
    start = threading.Barrier(N + H + 1)
    done = threading.Event()
    errors = []

    def worker():
        start.wait()
        try:
            for _ in range(M):
                with launch:
                    y = dist_spmv(dA, x)
                np.asarray(y)       # drain before the next launch
        except Exception as e:   # pragma: no cover - diagnostic
            errors.append(e)

    def hammer(i):
        start.wait()
        try:
            for k in range(200):
                with obs.span(f"hammer{i}.outer"):
                    with obs.span(f"hammer{i}.inner"):
                        counters.handle("cc.hammer").inc()
        except Exception as e:   # pragma: no cover - diagnostic
            errors.append(e)

    observations = []

    def observer():
        start.wait()
        while not done.is_set() and len(observations) < 2000:
            snap = counters.snapshot("comm.dist_spmv.")
            observations.append((snap.get("comm.dist_spmv.ppermute", 0),
                                 snap.get("comm.dist_spmv.ppermute_bytes",
                                          0)))
            time.sleep(0)

    threads = [threading.Thread(target=worker) for _ in range(N)]
    threads += [threading.Thread(target=hammer, args=(i,))
                for i in range(H)]
    obs_t = threading.Thread(target=observer)
    for t in threads:
        t.start()
    obs_t.start()
    for t in threads:
        t.join()
    done.set()
    obs_t.join()

    assert not errors, errors
    per_call = 2 * R * dA.halo * 4
    assert counters.get("op.dist_spmv") == N * M
    assert counters.get("comm.dist_spmv.ppermute") == N * M
    assert (counters.get("comm.dist_spmv.ppermute_bytes")
            == N * M * per_call)
    assert counters.get("cc.hammer") == H * 200
    recs = obs.records()
    spans = [r for r in recs if r["name"] == "dist_spmv"]
    assert len(spans) == N * M
    for i in range(H):
        inner = [r for r in recs if r["name"] == f"hammer{i}.inner"]
        assert len(inner) == 200
        assert all(r["depth"] == 1 for r in inner)
    # Records bump the call handle before the bytes handle, so a
    # snapshot may catch at most one in-flight record per worker:
    # bytes never EXCEED calls * per_call and lag by at most N.
    for calls, nbytes in observations:
        assert nbytes <= calls * per_call, "bytes ahead of calls"
        assert nbytes >= (calls - N) * per_call, "torn comm snapshot"
    counters.reset("cc.")
