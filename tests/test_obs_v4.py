# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Obs v4 drills: causal request flows, the SLO burn-rate evaluator,
the OpenMetrics round-trip + SIGTERM flush, and the performance
doctor (docs/OBSERVABILITY.md).

The load-bearing contracts, each pinned here:

- **causal flows**: one gateway request under OBS=1 yields a
  Chrome-trace flow arc (``ph s/t/f``, shared ``id``) connecting
  ``gateway.admit`` through ``gateway.batch`` to the dispatch — a
  single trace id across every hop;
- **SLO burn**: a latency-fault drill drives the evaluator to a
  deterministic breach verdict and the ``slo.breach.<slo>`` counter is
  EXACT (one evaluation, one increment); with
  ``LEGATE_SPARSE_TPU_OBS_SLO`` unset the evaluator is bit-for-bit
  inert — no verdicts, zero ``slo.*`` counter movement;
- **format pins**: ``parse_openmetrics`` round-trips
  ``render_openmetrics`` exactly (names, escaping, bucket counts), the
  scrape stays parseable and monotone under concurrent writers, and a
  SIGTERM'd process still leaves a parseable snapshot behind;
- **doctor**: the committed golden smoke artifact diagnoses to a
  deterministic finding set, and ``--check`` exit codes are a usable
  CI verdict.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

import legate_sparse_tpu as lst
from legate_sparse_tpu import obs, resilience
from legate_sparse_tpu.engine import Engine, Gateway
from legate_sparse_tpu.obs import (
    context, counters, export, latency, report, slo, trace,
)
from legate_sparse_tpu.resilience import faults as rfaults
from legate_sparse_tpu.settings import settings

from utils_test.tools import load_tool as _tool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "evidence", "BENCH_golden_smoke.json")

_ENG = Engine()


@pytest.fixture(autouse=True)
def _obs_isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    context.reset_ids()
    yield
    obs.reset_all()
    context.reset_ids()
    if was:
        trace.enable()
    else:
        trace.disable()


@pytest.fixture
def gw_on():
    saved = settings.gateway
    settings.gateway = True
    yield settings
    settings.gateway = saved


@pytest.fixture
def slo_on():
    saved = (settings.obs_slo, settings.obs_slo_watchdog_ms)
    settings.obs_slo = True
    settings.obs_slo_watchdog_ms = 0.0
    yield settings
    settings.obs_slo, settings.obs_slo_watchdog_ms = saved


def _random_csr(n=400, density=0.03, seed=0):
    S = sp.random(n, n, density=density, format="csr",
                  random_state=np.random.default_rng(seed),
                  dtype=np.float32)
    return lst.csr_array(S)


def _x(n, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(n).astype(np.float32))


def _gateway(**kw):
    base = dict(max_batch=64, queue_depth=128, tenant_quota=64,
                rate=0.0, burst=16.0, slack_ms=1.0, timeout_ms=0.0)
    base.update(kw)
    return Gateway(_ENG, **base)


# ------------------------------------------------------ trace context --
def test_mint_is_unique_and_joins_active_context():
    a = context.mint(rid=1)
    b = context.mint(rid=2)
    assert a.trace_id != b.trace_id
    with context.use(a):
        # A nested mint JOINS the active flow instead of forking it —
        # the executor request minted under a gateway context must
        # carry the gateway's id.
        assert context.mint(rid=3) is a
        assert context.current_trace_id() == a.trace_id
    assert context.current() is None


def test_trace_context_immutable_and_use_none_noop():
    c = context.mint()
    with pytest.raises(AttributeError):
        c.trace_id = "forged"
    with context.use(None):
        assert context.current() is None


def test_profiler_scope_nullcontext_without_active_context():
    import contextlib
    assert isinstance(context.profiler_scope("op"),
                      contextlib.nullcontext)


def test_spans_and_events_auto_tag_active_trace_id():
    obs.enable()
    c = context.mint()
    with context.use(c):
        with obs.span("tagme"):
            pass
        obs.event("tagme.event")
    with obs.span("untagged"):
        pass
    recs = {r["name"]: r for r in obs.records()}
    assert recs["tagme"]["attrs"]["trace_id"] == c.trace_id
    assert recs["tagme.event"]["attrs"]["trace_id"] == c.trace_id
    assert "trace_id" not in (recs["untagged"].get("attrs") or {})


def test_explicit_trace_ids_attr_wins_over_context():
    obs.enable()
    with context.use(context.mint()):
        with obs.span("batchlike", trace_ids=["a", "b"]):
            pass
    (rec,) = [r for r in obs.records() if r["name"] == "batchlike"]
    assert rec["attrs"]["trace_ids"] == ["a", "b"]
    assert "trace_id" not in rec["attrs"]


# -------------------------------------------------------- causal flows --
def test_causal_flow_arc_end_to_end(gw_on):
    """One gateway request under OBS=1 renders as a connected flow
    arc: ``ph "s"`` then ``"f"`` records sharing one id, and the
    ``gateway.admit`` / ``gateway.batch`` spans both carry that id."""
    obs.enable()
    gw = _gateway()
    A, x = _random_csr(), _x(400)
    fut = gw.submit(A, x, tenant="t0", qos="interactive")
    gw.flush()
    y = fut.result()
    np.testing.assert_allclose(np.asarray(y), np.asarray(A.dot(x)),
                               rtol=1e-5)

    doc = obs.to_chrome_trace()
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
    assert flows, "no flow records exported"
    ids = {e["id"] for e in flows}
    assert len(ids) == 1
    (tid,) = ids
    phases = [e["ph"] for e in sorted(flows, key=lambda e: e["ts"])]
    assert phases[0] == "s" and phases[-1] == "f"
    assert all(p == "t" for p in phases[1:-1])
    assert flows[-1].get("bp") == "e"  # bind to enclosing slice

    spans = {e["name"]: e for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    assert spans["gateway.admit"]["args"]["trace_id"] == tid
    assert tid in spans["gateway.batch"]["args"]["trace_ids"]


def test_flow_requires_two_anchors():
    """A trace id seen in only one span must NOT produce a dangling
    one-record arc."""
    obs.enable()
    with context.use(context.mint()):
        with obs.span("solo"):
            pass
    doc = obs.to_chrome_trace()
    assert not [e for e in doc["traceEvents"] if e.get("cat") == "flow"]


def test_distinct_requests_get_distinct_arcs(gw_on):
    obs.enable()
    gw = _gateway()
    A = _random_csr()
    futs = [gw.submit(A, _x(400, seed=s), tenant=f"t{s}",
                      qos="interactive") for s in range(2)]
    gw.flush()
    for f in futs:
        f.result()
    doc = obs.to_chrome_trace()
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
    assert len({e["id"] for e in flows}) == 2


# ------------------------------------------------------------ SLO burn --
def test_slo_breach_exact_counter(slo_on):
    """Deterministic drill: every observation above the objective →
    fast burn far past the page threshold → exactly one breach
    increment per evaluation that saw fresh bad events."""
    slo.register(slo.Slo(
        "drill", "drill.op", None, "lat.drill.", objective_ms=1.0,
        target=0.99))
    for _ in range(8):
        latency.observe("lat.drill.op", 50.0)
    verdicts = {v.slo: v for v in slo.evaluate()}
    v = verdicts["drill"]
    assert v.status == "breach"
    assert v.fast_bad == v.fast_total == 8
    assert v.fast_burn == pytest.approx((8 / 8) / 0.01)
    assert counters.get("slo.breach.drill") == 1

    # No fresh observations: the fast window is empty, no new breach,
    # the counter stays EXACT (slow window keeps it at watch).
    verdicts = {v.slo: v for v in slo.evaluate()}
    assert verdicts["drill"].status == "watch"
    assert verdicts["drill"].fast_total == 0
    assert counters.get("slo.breach.drill") == 1
    assert counters.get("slo.evaluations") == 2


def test_slo_ok_below_objective(slo_on):
    slo.register(slo.Slo(
        "calm", "calm.op", None, "lat.calm.", objective_ms=1000.0))
    for _ in range(10):
        latency.observe("lat.calm.op", 0.5)
    (v,) = [v for v in slo.evaluate() if v.slo == "calm"]
    assert v.status == "ok" and v.fast_bad == 0
    assert counters.get("slo.breach.calm") == 0


def test_slo_latency_fault_drill_breaches_gateway_objective(gw_on,
                                                            slo_on):
    """The resilience latency injector drives real ``lat.gateway.
    request.interactive`` observations past a tightened objective —
    the full pipeline (fault → histogram → burn → verdict → counter),
    not a hand-fed histogram."""
    saved_resil = settings.resil
    settings.resil = True
    resilience.reset()
    try:
        slo.register(slo.Slo(
            "gateway.interactive", "gateway.request", "interactive",
            "lat.gateway.request.interactive", objective_ms=1e-3,
            target=0.99))
        rfaults.inject("gateway.admit", kind="latency", count=3,
                       latency_ms=5.0)
        gw = _gateway()
        A = _random_csr()
        futs = [gw.submit(A, _x(400, seed=s), tenant="t0",
                          qos="interactive") for s in range(3)]
        gw.flush()
        for f in futs:
            f.result()
        verdicts = {v.slo: v for v in slo.evaluate()}
        v = verdicts["gateway.interactive"]
        assert v.status == "breach"
        assert v.fast_bad == v.fast_total >= 3
        assert counters.get("slo.breach.gateway.interactive") == 1
    finally:
        settings.resil = saved_resil
        resilience.reset()


def test_slo_inert_by_default(gw_on):
    """LEGATE_SPARSE_TPU_OBS_SLO unset: the evaluator returns [] and
    no ``slo.*`` counter exists, while the gateway result stays
    bit-for-bit the plain dot — v4 costs nothing when off."""
    assert settings.obs_slo is False
    for _ in range(5):
        latency.observe("lat.gateway.request.interactive", 1e6)
    assert slo.evaluate() == []
    assert slo.verdicts() == []
    assert slo.start_watchdog(10.0) is False
    gw = _gateway()
    A, x = _random_csr(), _x(400)
    fut = gw.submit(A, x, tenant="t", qos="interactive")
    gw.flush()
    y_off = np.asarray(fut.result())
    snap = counters.snapshot()
    assert not [k for k in snap if k.startswith("slo.")]
    # The scrape path calls evaluate() unconditionally — still inert.
    text = export.snapshot_openmetrics()
    assert "slo." not in text
    # Arming the evaluator changes nothing numerically: the identical
    # submit under OBS_SLO=1 (with a scrape-triggered evaluation in
    # between) is bit-for-bit the unarmed result.
    settings.obs_slo = True
    try:
        export.snapshot_openmetrics()
        fut = gw.submit(A, x, tenant="t", qos="interactive")
        gw.flush()
        y_on = np.asarray(fut.result())
    finally:
        settings.obs_slo = False
    assert np.array_equal(y_off, y_on)


def test_slo_watchdog_ticks_and_stops(slo_on):
    slo.register(slo.Slo(
        "wd", "wd.op", None, "lat.wd.", objective_ms=1000.0))
    latency.observe("lat.wd.op", 0.1)
    assert slo.start_watchdog(5.0) is True
    assert slo.start_watchdog(5.0) is True  # idempotent while alive
    deadline = time.monotonic() + 5.0
    while (counters.get("slo.watchdog.ticks") < 2
           and time.monotonic() < deadline):
        time.sleep(0.01)
    slo.stop_watchdog()
    ticks = counters.get("slo.watchdog.ticks")
    assert ticks >= 2
    assert counters.get("slo.evaluations") >= ticks
    time.sleep(0.05)
    assert counters.get("slo.watchdog.ticks") == ticks  # really dead


def test_slo_register_replaces_and_resets():
    tightened = slo.Slo("gateway.interactive", "gateway.request",
                        "interactive",
                        "lat.gateway.request.interactive",
                        objective_ms=1.0)
    slo.register(tightened)
    byname = {s.name: s for s in slo.registered()}
    assert byname["gateway.interactive"].objective_ms == 1.0
    slo.reset()
    byname = {s.name: s for s in slo.registered()}
    assert byname["gateway.interactive"].objective_ms == 50.0


# ------------------------------------------------- OpenMetrics format --
def test_openmetrics_round_trip_exact():
    counters.inc("rt.plain", 3)
    counters.inc('rt.wei"rd\\name', 2)
    for ms in (0.5, 1.5, 200.0):
        latency.observe("lat.rt.op", ms)
    text = export.render_openmetrics()
    parsed_counters, parsed_hists = export.parse_openmetrics(text)
    snap = counters.snapshot()
    for name, val in snap.items():
        assert parsed_counters[name] == val
    h = parsed_hists["lat.rt.op"]
    assert h["count"] == 3
    assert h["sum"] == pytest.approx(202.0)
    # Cumulative bucket counts ascend and end at +Inf == count.
    bounds = [b for b, _ in h["buckets"]]
    cums = [c for _, c in h["buckets"]]
    assert bounds == sorted(bounds) and bounds[-1] == float("inf")
    assert cums == sorted(cums) and cums[-1] == 3


def test_parse_openmetrics_rejects_garbage_and_missing_eof():
    with pytest.raises(ValueError, match="unparseable"):
        export.parse_openmetrics("not a metric line\n# EOF\n")
    with pytest.raises(ValueError, match="EOF"):
        export.parse_openmetrics(
            'legate_sparse_tpu_counter_total{name="x"} 1\n')


def test_openmetrics_type_help_lines_pinned():
    text = export.render_openmetrics()
    lines = text.splitlines()
    assert "# TYPE legate_sparse_tpu_counter counter" in lines
    assert "# TYPE legate_sparse_tpu_latency histogram" in lines
    assert any(ln.startswith("# HELP legate_sparse_tpu_counter ")
               for ln in lines)
    assert any(ln.startswith("# HELP legate_sparse_tpu_latency ")
               for ln in lines)
    assert lines[-1] == "# EOF"


def test_concurrent_scrape_always_parses_and_is_monotone():
    """Writers hammer counters + histograms while the main thread
    scrapes repeatedly: every scrape parses, and every counter /
    histogram total is nondecreasing across consecutive scrapes."""
    N, M = 4, 800
    stop = threading.Event()

    def writer(k):
        i = 0
        while not stop.is_set() and i < M:
            counters.inc("scr.events")
            counters.inc(f"scr.w{k}")
            latency.observe("lat.scr.op", 0.25 * (1 + (i % 7)))
            i += 1

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(N)]
    for t in threads:
        t.start()
    try:
        prev_counters, prev_count = {}, 0
        for _ in range(25):
            parsed_c, parsed_h = export.parse_openmetrics(
                export.snapshot_openmetrics())
            for name, val in prev_counters.items():
                assert parsed_c.get(name, 0) >= val, name
            prev_counters = {k: v for k, v in parsed_c.items()
                             if k.startswith("scr.")}
            cnt = parsed_h.get("lat.scr.op", {}).get("count", 0)
            assert cnt >= prev_count
            prev_count = cnt
    finally:
        stop.set()
        for t in threads:
            t.join()
    parsed_c, parsed_h = export.parse_openmetrics(
        export.snapshot_openmetrics())
    assert parsed_c["scr.events"] == N * M
    assert parsed_h["lat.scr.op"]["count"] == N * M


def test_sigterm_flushes_openmetrics_snapshot(tmp_path):
    """Containerized runs die by SIGTERM, not sys.exit: the chained
    handler must flush the snapshot AND still die by the signal."""
    prom = tmp_path / "term.prom"
    child = (
        "import os, signal\n"
        "from legate_sparse_tpu.obs import counters\n"
        "counters.inc('sig.test', 7)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "raise SystemExit('survived SIGTERM')\n"
    )
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               LEGATE_SPARSE_TPU_OBS_PROM=str(prom))
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=REPO)
    assert r.returncode == -signal.SIGTERM, (r.returncode, r.stderr)
    assert prom.exists(), "SIGTERM left no snapshot behind"
    parsed_c, _ = export.parse_openmetrics(prom.read_text())
    assert parsed_c["sig.test"] == 7


# ----------------------------------------------------- flow/slo tables --
def test_load_records_maps_flow_phases():
    obs.enable()
    c = context.mint()
    with context.use(c):
        with obs.span("a"):
            pass
        with obs.span("b"):
            pass
    doc = obs.to_chrome_trace()
    path = "/tmp/does-not-matter"
    # Exercise load_records via its parsing body, not the file system.
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(doc, f)
        path = f.name
    try:
        recs = report.load_records(path)
    finally:
        os.unlink(path)
    kinds = {r["type"] for r in recs}
    assert "flow" in kinds and "span" in kinds
    flows = [r for r in recs if r["type"] == "flow"]
    assert all(r["flow_id"] == c.trace_id for r in flows)
    # Flow anchors must not leak into the per-op aggregation.
    agg = report.aggregate(recs)
    assert "request" not in agg
    assert agg["a"]["calls"] == 1


def test_render_flows_table_groups_by_trace_id():
    records = [
        {"type": "span", "name": "gateway.admit", "ts_ns": 0,
         "dur_ns": 2e6, "attrs": {"trace_id": "req-1"}},
        {"type": "span", "name": "gateway.batch", "ts_ns": 3e6,
         "dur_ns": 4e6, "attrs": {"trace_ids": ["req-1", "req-2"]}},
        {"type": "span", "name": "gateway.admit", "ts_ns": 1e6,
         "dur_ns": 1e6, "attrs": {"trace_id": "req-2"}},
    ]
    out = report.render_flows_table(records)
    lines = out.splitlines()
    assert lines[0].split()[:4] == ["flow", "spans", "first", "last"]
    row1 = next(ln for ln in lines if ln.startswith("req-1"))
    assert row1.split()[1] == "2"
    assert "gateway.admit" in row1 and "gateway.batch" in row1
    # req-1: wall = (3ms + 4ms) - 0 = 7ms
    assert "7.000" in row1
    assert report.render_flows_table([]).startswith(
        "no trace-tagged spans")


def test_render_slo_table_from_events_and_counters():
    records = [
        {"type": "event", "name": "slo.verdict",
         "attrs": {"slo": "gateway.interactive", "status": "breach",
                   "objective_ms": 50.0, "fast_bad": 6,
                   "fast_total": 6, "fast_burn": 1000.0,
                   "slow_burn": 900.0}},
    ]
    table = report.render_slo_table(
        {"slo.breach.gateway.interactive": 2, "slo.evaluations": 4},
        records)
    assert "gateway.interactive" in table
    assert "breach" in table
    assert "evaluations: 4" in table
    empty = report.render_slo_table({}, [])
    assert empty.startswith("no slo.* activity")


def test_trace_summary_flows_and_slo_flags(gw_on, slo_on, tmp_path,
                                           capsys):
    obs.enable()
    slo.register(slo.Slo(
        "gateway.interactive", "gateway.request", "interactive",
        "lat.gateway.request.interactive", objective_ms=1e-6))
    gw = _gateway()
    A, x = _random_csr(), _x(400)
    fut = gw.submit(A, x, tenant="t0", qos="interactive")
    gw.flush()
    fut.result()
    slo.evaluate()
    path = str(tmp_path / "run.trace.json")
    obs.write_chrome_trace(path)
    ts = _tool("trace_summary")
    rc = ts.main([path, "--flows", "--slo"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "causal flows:" in out and "req-" in out
    assert "slo ledger:" in out and "gateway.interactive" in out


def test_obs_overhead_rides_trajectory_ungated():
    """``obs_overhead_pct`` is an informational trajectory column
    (bench schema 14), never a regression gate — a noisy micro-probe
    must not fail CI."""
    from legate_sparse_tpu.obs import regress
    assert "obs_overhead_pct" in regress.TRAJECTORY_FIELDS
    assert regress._gated("obs_overhead_pct", 11.0) is None


# -------------------------------------------------------------- doctor --
def test_doctor_golden_smoke_findings_deterministic(capsys):
    """The committed golden artifact must diagnose to a stable finding
    set — this is the tier-1 CI hook the ISSUE asks for."""
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    assert doctor.load_artifact(GOLDEN, ev) == "bench"
    findings = doctor.diagnose(ev)
    codes = [f["code"] for f in findings]
    assert codes == ["breaker-trips", "gateway-rejections"]
    assert all(f["severity"] == "warn" for f in findings)
    # CI verdict: warns alone don't fail the default --check.
    assert doctor.main(["--check", GOLDEN]) == 0
    assert doctor.main(["--check", "--fail-on", "warn", GOLDEN]) == 1
    capsys.readouterr()


def test_doctor_flags_slo_breach_as_critical(tmp_path, capsys):
    counters.inc("slo.breach.gateway.interactive", 3)
    prom = tmp_path / "m.prom"
    export.write_openmetrics(str(prom))
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    assert doctor.load_artifact(str(prom), ev) == "openmetrics"
    findings = doctor.diagnose(ev)
    assert findings[0]["code"] == "slo-breach"
    assert findings[0]["severity"] == "critical"
    assert doctor.main(["--check", str(prom)]) == 1
    capsys.readouterr()


def test_doctor_reads_trace_artifacts_and_ranks(tmp_path, capsys):
    obs.enable()
    counters.inc("resil.breaker.trips", 2)
    counters.inc("slo.breach.engine.request", 1)
    with obs.span("op.spmv"):
        pass
    path = str(tmp_path / "t.trace.json")
    obs.write_chrome_trace(path)
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    assert doctor.load_artifact(path, ev) == "trace"
    findings = doctor.diagnose(ev)
    codes = [f["code"] for f in findings]
    # Ranked: critical first.
    assert codes[0] == "slo-breach" and "breaker-trips" in codes
    capsys.readouterr()


def test_doctor_healthy_artifact_no_findings(tmp_path, capsys):
    bench = {"schema_version": 14, "metric": "x", "value": 1.0,
             "engine_plan_hits": 9, "engine_plan_misses": 1}
    p = tmp_path / "ok.json"
    p.write_text(json.dumps(bench))
    doctor = _tool("doctor")
    assert doctor.main(["--check", "--fail-on", "info", str(p)]) == 0
    out = capsys.readouterr().out
    assert "no findings" in out


def test_doctor_unreadable_artifacts_exit_2(tmp_path, capsys):
    p = tmp_path / "junk.bin"
    p.write_text("not json, not openmetrics")
    doctor = _tool("doctor")
    assert doctor.main([str(p)]) == 2
    capsys.readouterr()


def test_doctor_obs_overhead_and_roofline_rules():
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    ev.bench.update({
        "obs_overhead_pct": 12.5,
        "cpu_roofline_ratio": 0.4,
        "cpu_roofline_items": {"mask_ms": 0.5, "pad_ms": 1.5},
    })
    codes = {f["code"]: f for f in doctor.diagnose(ev)}
    assert "obs-overhead" in codes
    roof = codes["roofline-shortfall"]
    # Loss terms ranked largest-first in the message.
    assert roof["message"].index("pad_ms") < roof["message"].index(
        "mask_ms")


def test_doctor_recovery_without_checkpoint_advance():
    """Device-loss recoveries with zero checkpoint saves mean every
    recovery replayed the whole solve from x0 — one warn finding
    pointing at the cadence knob; quiet once snapshots advance."""
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    ev.bench.update({"resil_recoveries": 2, "resil_ckpt_saves": 0})
    codes = {f["code"]: f for f in doctor.diagnose(ev)}
    f = codes["recovery-without-checkpoint-advance"]
    assert f["severity"] == "warn"
    assert "CKPT_ITERS" in f["hint"]
    ev2 = doctor.Evidence()
    ev2.bench.update({"resil_recoveries": 2, "resil_ckpt_saves": 4})
    assert "recovery-without-checkpoint-advance" not in {
        f["code"] for f in doctor.diagnose(ev2)}


def _verdict_rec(key, label):
    return {"type": "event", "name": "autotune.verdict", "ts_ns": 0,
            "tid": 0, "attrs": {"key": key, "label": label}}


def test_doctor_storage_wider_than_verdict():
    """An f32-storage verdict for a fingerprint class that also holds
    a bf16-storage verdict is the compressed-storage win sitting idle
    — one warn finding per class, hint pointing at compress()."""
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    ev.records = [
        _verdict_rec(
            "spmv/bfloat16/banded/w8/r64/z256/k1/si16@cpu:cpu:8/e0",
            "csr-rowids-bf16"),
        _verdict_rec(
            "spmv/float32/banded/w8/r64/z256/k1@cpu:cpu:8/e0",
            "csr-rowids"),
        # Different fingerprint class: silent.
        _verdict_rec(
            "spmv/float32/powerlaw/w64/r262144/z2097152/k1@cpu:cpu:8/e0",
            "sliced-ell"),
        # Unparseable key: skipped, never crashes.
        _verdict_rec("garbage", "x"),
    ]
    found = [f for f in doctor.diagnose(ev)
             if f["code"] == "storage-wider-than-verdict"]
    assert len(found) == 1
    f = found[0]
    assert f["severity"] == "warn"
    assert "banded/w8" in f["message"]
    assert "csr-rowids-bf16" in f["message"]
    assert "compress()" in f["hint"]
    # The storage tag and platform/epoch are structural no-ops: keys
    # differing only there land in the same class.
    assert doctor._parse_verdict_key(
        "spmv/bfloat16/banded/w8/r64/z256/k1/si16@cpu:cpu:8/e0"
    ) == doctor._parse_verdict_key(
        "spmv/bfloat16/banded/w8/r64/z256/k1@tpu:v5p:64/e3")


def test_doctor_storage_rule_quiet_without_f32_twin():
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    ev.records = [_verdict_rec(
        "spmv/bfloat16/banded/w8/r64/z256/k1/si16@cpu:cpu:8/e0",
        "csr-rowids-bf16")]
    assert not [f for f in doctor.diagnose(ev)
                if f["code"] == "storage-wider-than-verdict"]
