# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Off-chip de-risking of the r3 on-chip Pallas worker fault (VERDICT r4 #2).

Three permanent gates, one subprocess per band-variant ladder rung
(the roll/inputs knobs are trace-time environment, exactly as the
bench canary ladder runs them):

1. **TPU lowering**: every rung's kernels (SpMV masked+unmasked, SpMM,
   banded SpGEMM) and the exact looped composition that crashed r3
   (kernel chained in a jitted ``fori_loop`` at the bench trip counts
   2/6/24, production tile 2^14, 2^24 rows) must lower + serialize for
   the TPU platform via ``jax.export`` — no chip needed.  This catches
   Mosaic verification errors (it already caught the i64 roll-shift
   bind) so a live tunnel window is spent measuring, not bisecting.

2. **Interpret-mode execution** of the same chained composition (same
   trip counts; tile forced to 1024 so the grid is still multi-step at
   a CPU-feasible 2^14 rows) with numeric checks against scipy.

3. **Distributed TPU lowering**: the full distributed composition —
   shard_map + ppermute halo + the per-shard Mosaic band kernel over
   the prepacked layout, the solver-shaped fori_loop nesting, and
   dist SpMM — must likewise export for the TPU platform (the dist
   lanes otherwise only ever run interpret mode).

The r3 fault signature: eager full-size launches PASS; the jitted
fori_loop composition crashes the worker (see ROUND3_NOTES.md and
``bench.py::_CANARY_CODE``).
"""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# The alternate-lowering rungs ride the slow lane: each rung is a
# full subprocess compile of the same compositions, and tier-1 keeps
# one representative on its single-core budget (pyproject addopts).
RUNGS = [
    ("pallas", {}),
    pytest.param("pallas-shift3",
                 {"LEGATE_SPARSE_TPU_PALLAS_INPUTS": "distinct"},
                 marks=pytest.mark.slow),
    pytest.param("pallas-jroll",
                 {"LEGATE_SPARSE_TPU_PALLAS_ROLL": "xla"},
                 marks=pytest.mark.slow),
]
RUNG_IDS = ["pallas", "pallas-shift3", "pallas-jroll"]


def _run(code: str, env_extra: dict, timeout_s: int = 420) -> None:
    env = dict(os.environ)
    env.update(env_extra)
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=timeout_s,
                       env=env)
    assert r.returncode == 0 and "all-ok" in r.stdout, (
        f"rc={r.returncode}\nstdout: {r.stdout[-1500:]}\n"
        f"stderr: {r.stderr[-3000:]}"
    )


# TPU-platform serialization of every kernel + the crash-shaped looped
# composition at the PRODUCTION shapes (2^24 rows, tile 2^14: abstract
# avals only — nothing is materialized).
_EXPORT_CODE = r"""
from legate_sparse_tpu._platform import pin_cpu
pin_cpu(1)
from functools import partial
import numpy as np
import jax, jax.numpy as jnp
import jax.export as jex
from legate_sparse_tpu.ops import pallas_dia

W = 11
offsets = tuple(range(-(W // 2), W // 2 + 1))
tile = pallas_dia.supported(offsets, np.float32, masked=False)
assert tile == 1 << 14, tile          # the production bench tile
n = 1 << 24                           # the production bench rows
rows_pad = -(-n // tile) * tile
rdata = jax.ShapeDtypeStruct((W, rows_pad // 128, 128), jnp.float32)
rmask = jax.ShapeDtypeStruct((W, rows_pad // 128, 128), jnp.int8)
x = jax.ShapeDtypeStruct((n,), jnp.float32)


def spmv(rd, v):
    return pallas_dia.pallas_dia_spmv(rd, None, v, offsets, (n, n), tile)


def spmv_masked(rd, rm, v):
    return pallas_dia.pallas_dia_spmv(rd, rm, v, offsets, (n, n), tile)


assert jex.export(jax.jit(spmv), platforms=["tpu"])(rdata, x).serialize()
assert jex.export(jax.jit(spmv_masked), platforms=["tpu"])(
    rdata, rmask, x).serialize()

# The r3 crash composition: kernel chained inside one jitted fori_loop,
# at the bench/canary trip counts (k_lo=2, k_hi=6, k_cap=24).
def loop(rd, v, k):
    out = jax.lax.fori_loop(0, k, lambda i, u: spmv(rd, u), v)
    return jnp.ravel(out)[0]

for k in (2, 6, 24):
    assert jex.export(jax.jit(partial(loop, k=k)),
                      platforms=["tpu"])(rdata, x).serialize()

# SpMM kernel (k=4 RHS — the canary's width) + its short loop.
kk = 4
mm_tile = 1024
X = jax.ShapeDtypeStruct((n, kk), jnp.float32)


def spmm(rd, V):
    return pallas_dia.pallas_dia_spmm(rd, None, V, offsets, (n, n),
                                      mm_tile)


def mm_loop(rd, V):
    return jax.lax.fori_loop(0, 8, lambda i, U: spmm(rd, U), V)

assert jex.export(jax.jit(spmm), platforms=["tpu"])(rdata, X).serialize()
assert jex.export(jax.jit(mm_loop), platforms=["tpu"])(rdata, X).serialize()

# Banded SpGEMM at the canary's reduced size.
ng = 1 << 22
offs_c = tuple(sorted({a + b for a in offsets for b in offsets}))
gg_tile = pallas_dia._spgemm_tile(offsets, W, W, len(offs_c),
                                  np.dtype(np.float32))
assert gg_tile is not None
band = jax.ShapeDtypeStruct((W, ng), jnp.float32)


def spgemm(b):
    return pallas_dia.pallas_dia_spgemm(b, b, offsets, offsets, offs_c,
                                        (ng, ng), (ng, ng), gg_tile)

assert jex.export(jax.jit(spgemm), platforms=["tpu"])(band).serialize()
print("all-ok")
"""


# Interpret-mode execution of the crash-shaped composition with numeric
# verification.  Tile forced to 1024 keeps the grid multi-step (16
# steps at 2^14 rows) at CPU-interpretable cost; trip counts are the
# production 2/6/24.
_INTERP_CODE = r"""
import os
os.environ["LEGATE_SPARSE_TPU_PALLAS_TILE"] = "1024"
from legate_sparse_tpu._platform import pin_cpu
pin_cpu(1)
import numpy as np
import scipy.sparse as sp
import jax, jax.numpy as jnp
from legate_sparse_tpu.ops import pallas_dia

W = 11
half = W // 2
offsets = tuple(range(-half, half + 1))
tile = pallas_dia.supported(offsets, np.float32, masked=False)
assert tile == 1024, tile
n = 1 << 14
assert n // tile == 16                # multi-step grid, like production

rng = np.random.default_rng(7)
# Scipy column-aligned DIA layout, magnitude-stable rows.
dia_data = (rng.uniform(0.5, 1.0, (W, n)) / W).astype(np.float32)
A = sp.dia_array((dia_data, offsets), shape=(n, n)).tocsr()
rdata, _ = pallas_dia.row_align(jnp.asarray(dia_data), offsets, (n, n),
                                tile)
x_np = rng.uniform(-1.0, 1.0, n).astype(np.float32)
x = jnp.asarray(x_np)


def step(v):
    return pallas_dia.pallas_dia_spmv(rdata, None, v, offsets, (n, n),
                                      tile, interpret=True)

# Eager launch (passed on-chip in r3) ...
y = np.asarray(step(x))
np.testing.assert_allclose(y, A @ x_np, rtol=2e-4, atol=1e-5)

# ... then the chained fori_loop composition (crashed on-chip in r3),
# at the bench/canary trip counts.
for k in (2, 6, 24):
    yk = np.asarray(jax.jit(
        lambda v: jax.lax.fori_loop(0, k, lambda i, u: step(u), v)
    )(x))
    ref = x_np.copy()
    for _ in range(k):
        ref = A @ ref
    np.testing.assert_allclose(yk, ref, rtol=5e-3, atol=1e-5)

# Masked variant (band with holes) through the same composition.
mask = (rng.uniform(size=(W, n)) > 0.2)
dia_masked = np.where(mask, dia_data, 0.0).astype(np.float32)
Am = sp.dia_array((dia_masked, offsets), shape=(n, n)).tocsr()
rd_m, rm_m = pallas_dia.row_align(
    jnp.asarray(dia_masked), offsets, (n, n), tile,
    mask=jnp.asarray(mask), with_mask=True)


def mstep(v):
    return pallas_dia.pallas_dia_spmv(rd_m, rm_m, v, offsets, (n, n),
                                      tile, interpret=True)

ym = np.asarray(jax.jit(
    lambda v: jax.lax.fori_loop(0, 6, lambda i, u: mstep(u), v))(x))
refm = x_np.copy()
for _ in range(6):
    refm = Am @ refm
np.testing.assert_allclose(ym, refm, rtol=5e-3, atol=1e-5)

# SpMM kernel in its loop (canary trip count 8).
kk = 4
X0 = rng.uniform(-1.0, 1.0, (n, kk)).astype(np.float32)


def mm_step(V):
    return pallas_dia.pallas_dia_spmm(rdata, None, V, offsets, (n, n),
                                      tile, interpret=True)

Ym = np.asarray(jax.jit(
    lambda V: jax.lax.fori_loop(0, 8, lambda i, U: mm_step(U), V)
)(jnp.asarray(X0)))
refM = X0.copy()
for _ in range(8):
    refM = A @ refM
np.testing.assert_allclose(Ym, refM, rtol=5e-3, atol=1e-5)

# Banded SpGEMM, carry-dependent loop (canary trip count 4; the
# operand depends on the carry so the kernel stays inside the loop).
offs_c = tuple(sorted({a + b for a in offsets for b in offsets}))
gg_tile = pallas_dia._spgemm_tile(offsets, W, W, len(offs_c),
                                  np.dtype(np.float32))
assert gg_tile is not None
band = jnp.asarray(dia_data)


def gg(b):
    return pallas_dia.pallas_dia_spgemm(
        b, band, offsets, offsets, offs_c, (n, n), (n, n), gg_tile,
        interpret=True)

C_dia = np.asarray(gg(band))
C_ref = (sp.dia_array((dia_data, offsets), shape=(n, n)) @
         sp.dia_array((dia_data, offsets), shape=(n, n))).todia()
# Align reference rows to offs_c ordering.
ref_rows = {int(o): C_ref.data[i] for i, o in enumerate(C_ref.offsets)}
for i, o in enumerate(offs_c):
    got = C_dia[i]
    want = ref_rows.get(int(o), np.zeros(n, np.float32))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)

final = jax.jit(lambda c: jnp.sum(jax.lax.fori_loop(
    0, 4,
    lambda i, c: c * 0.5 + gg(
        band.at[0, 0].add((c[0, 0] * 1e-30).astype(band.dtype)))[0][:1],
    c)))(jnp.zeros((1, n), dtype=jnp.float32))
assert bool(jnp.isfinite(final))
print("all-ok")
"""


@pytest.mark.parametrize("name,env_extra", RUNGS,
                         ids=RUNG_IDS)
def test_tpu_export_every_rung(name, env_extra):
    """Every ladder rung's kernels + the r3 crash composition must
    lower and serialize for the TPU platform from this CPU host."""
    _run(_EXPORT_CODE, env_extra)


@pytest.mark.parametrize("name,env_extra", RUNGS,
                         ids=RUNG_IDS)
def test_interpret_crash_composition_every_rung(name, env_extra):
    """The exact chained-fori_loop composition that crashed the r3
    worker, executed (interpret mode) with numeric checks, per rung."""
    env = dict(env_extra)
    env["LEGATE_SPARSE_TPU_PALLAS_DIA"] = "interpret"
    _run(_INTERP_CODE, env)


# The DISTRIBUTED Mosaic route (shard_map + ppermute halo + the
# per-shard Pallas band kernel over the prepacked layout) has never
# executed compiled anywhere (VERDICT r4 weak #4: dist lanes run
# interpret mode).  This gate proves the full composition at least
# LOWERS + SERIALIZES for the TPU platform from the CPU host, for
# every band-variant rung — so a tunnel window spends its minutes
# measuring, not discovering Mosaic lowering bugs in the dist path.
_DIST_EXPORT_CODE = r"""
import os
os.environ["LEGATE_SPARSE_TPU_PALLAS_DIST"] = "1"
from legate_sparse_tpu._platform import pin_cpu
pin_cpu(8)
import numpy as np
import jax, jax.numpy as jnp
import jax.export as jex
import legate_sparse_tpu as sparse
from legate_sparse_tpu.parallel import make_row_mesh, shard_csr
from legate_sparse_tpu.parallel.dist_csr import dist_spmm, dist_spmv
from jax.sharding import NamedSharding, PartitionSpec as P

n = 1 << 16
W = 11
half = W // 2
offs = list(range(-half, half + 1))
diags = [np.full(n - abs(o), 1.0 / W, np.float32) for o in offs]
A = sparse.diags(diags, offs, shape=(n, n), format="csr",
                 dtype=np.float32)
mesh = make_row_mesh(jax.devices()[:8])
dA = shard_csr(A, mesh=mesh)
assert dA.pdia_tile, "Mosaic dist prepack must engage for this band"
sh = NamedSharding(mesh, P("rows"))

xa = jax.ShapeDtypeStruct((dA.rows_padded,), jnp.float32, sharding=sh)
exp = jex.export(jax.jit(lambda x: dist_spmv(dA, x)),
                 platforms=["tpu"])(xa)
assert exp.serialize()

# The looped composition (solver-shaped: the kernel inside fori_loop
# inside shard_map-consuming jit) — the r3 fault shape, distributed.
def loop(x):
    out = jax.lax.fori_loop(0, 6, lambda i, v: dist_spmv(dA, v), x)
    return jnp.ravel(out)[0]

assert jex.export(jax.jit(loop), platforms=["tpu"])(xa).serialize()

# Dist SpMM over the same prepack.
Xa = jax.ShapeDtypeStruct((dA.rows_padded, 4), jnp.float32,
                          sharding=NamedSharding(mesh, P("rows", None)))
assert jex.export(jax.jit(lambda X: dist_spmm(dA, X)),
                  platforms=["tpu"])(Xa).serialize()
print("all-ok")
"""


@pytest.mark.parametrize("name,env_extra", RUNGS,
                         ids=RUNG_IDS)
def test_dist_mosaic_tpu_export_every_rung(name, env_extra):
    """Distributed shard_map + Pallas band SpMV/SpMM (and the looped
    solver composition) must lower and serialize for the TPU platform
    from this CPU host, per band-variant rung."""
    _run(_DIST_EXPORT_CODE, env_extra)
