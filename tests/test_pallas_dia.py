# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Differential tests for the Pallas DIA SpMV kernel (interpret mode).

The exact kernel logic (roll-based shifts, row-aligned layout, boundary
validity, hole masks) runs on CPU via ``interpret=True`` — the same
discipline as the reference testing its CUDA leaf tasks through the
integration suite, but at kernel granularity.
"""

import numpy as np
import pytest
import scipy.sparse as scsp

import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu.ops import pallas_dia


def _spmv_via_pallas(A, x):
    """Run A @ x through the pallas kernel in interpret mode."""
    dia = A._get_dia()
    assert dia is not None, "matrix must be band-detected"
    dia_data, offsets, mask = dia
    packed = pallas_dia.pack_band(dia_data, offsets, A.shape, mask=mask)
    assert packed is not None, "kernel must support this band"
    return np.asarray(
        pallas_dia.pallas_dia_spmv(
            packed.rdata, packed.rmask, jnp.asarray(x), packed.offsets,
            packed.shape, packed.tile, interpret=True,
        )
    )


def _banded(n, offsets, rng, dtype=np.float32, m=None):
    m = n if m is None else m
    diags = [rng.standard_normal(max(n, m)).astype(dtype) for _ in offsets]
    A_sp = scsp.diags(diags, offsets, shape=(n, m), format="csr",
                      dtype=dtype)
    return sparse.csr_array(A_sp), A_sp


@pytest.mark.parametrize("n", [64, 1000, 5000])
@pytest.mark.parametrize("offsets", [(-1, 0, 1), (-5, -1, 0, 1, 5),
                                     (0,), (-37, 2)])
def test_exact_band_matches_scipy(n, offsets, rng):
    A, A_sp = _banded(n, list(offsets), rng)
    x = rng.standard_normal(n).astype(np.float32)
    y = _spmv_via_pallas(A, x)
    np.testing.assert_allclose(y, A_sp @ x, rtol=2e-5, atol=2e-5)


def test_large_offsets_multirow_shift(rng):
    # Offsets beyond one lane row (|off| > 128) exercise the sublane
    # (q) component of the shift decomposition.
    n = 4096
    offsets = [-1030, -129, -128, -127, 0, 127, 128, 129, 1030]
    A, A_sp = _banded(n, offsets, rng)
    x = rng.standard_normal(n).astype(np.float32)
    y = _spmv_via_pallas(A, x)
    np.testing.assert_allclose(y, A_sp @ x, rtol=2e-5, atol=2e-5)


def test_holey_band_mask(rng):
    # diags().tocsr() drops interior zeros -> holes -> masked variant.
    n = 600
    main = rng.standard_normal(n).astype(np.float32)
    off1 = rng.standard_normal(n - 1).astype(np.float32)
    off1[::7] = 0.0
    A_sp = scsp.diags([main, off1, off1], [0, 1, -1], format="csr")
    A_sp.eliminate_zeros()
    A = sparse.csr_array(A_sp)
    dia = A._get_dia()
    assert dia is not None and dia[2] is not None, "expect holey band"
    x = rng.standard_normal(n).astype(np.float32)
    y = _spmv_via_pallas(A, x)
    np.testing.assert_allclose(y, A_sp @ x, rtol=2e-5, atol=2e-5)


def test_holey_band_ieee_nonfinite_x(rng):
    # A hole must never multiply x: an inf parked on a hole column in a
    # row that has no entry there must not propagate NaN into that row.
    n = 256
    main = np.ones(n, np.float32)
    off1 = np.ones(n - 1, np.float32)
    off1[10] = 0.0  # hole at (10, 11)
    A_sp = scsp.diags([main, off1], [0, 1], format="csr")
    A_sp.eliminate_zeros()
    A = sparse.csr_array(A_sp)
    x = np.ones(n, np.float32)
    x[11] = np.inf
    y = _spmv_via_pallas(A, x)
    y_ref = A_sp @ x
    # Row 10 references only column 10 -> finite.
    assert np.isfinite(y[10]), y[10]
    assert y[10] == y_ref[10]
    # Rows 11 (diag) and 10's neighbors referencing column 11 see inf.
    assert np.isinf(y[11])


def test_boundary_edges_zeroed(rng):
    # First/last rows: shifts reach outside [0, n) and must contribute
    # exactly zero even though the clamped neighbor tiles hold real
    # (finite) x values.
    n = 300
    A, A_sp = _banded(n, [-2, 0, 3], rng)
    x = rng.standard_normal(n).astype(np.float32)
    y = _spmv_via_pallas(A, x)
    np.testing.assert_allclose(y, A_sp @ x, rtol=2e-5, atol=2e-5)


def test_rectangular_shapes(rng):
    for (n, m) in [(200, 300), (300, 200)]:
        A, A_sp = _banded(n, [-1, 0, 1], rng, m=m)
        x = rng.standard_normal(m).astype(np.float32)
        y = _spmv_via_pallas(A, x)
        np.testing.assert_allclose(y, A_sp @ x, rtol=2e-5, atol=2e-5)


def test_bfloat16_supported(rng):
    n = 512
    diags = [np.ones(n, np.float32), np.full(n, 0.5, np.float32)]
    A_sp = scsp.diags(diags, [0, 1], shape=(n, n), format="csr")
    A = sparse.csr_array(A_sp).astype(jnp.bfloat16)
    x = jnp.ones((n,), jnp.bfloat16)
    dia = A._get_dia()
    dia_data, offsets, mask = dia
    packed = pallas_dia.pack_band(dia_data, offsets, A.shape, mask=mask)
    assert packed is not None
    y = np.asarray(
        pallas_dia.pallas_dia_spmv(
            packed.rdata, packed.rmask, x, packed.offsets, packed.shape,
            packed.tile, interpret=True,
        ).astype(jnp.float32)
    )
    y_ref = np.asarray(A_sp @ np.ones(n, np.float32))
    np.testing.assert_allclose(y, y_ref, rtol=2e-2)


def test_f64_unsupported():
    assert pallas_dia.supported((0, 1), np.float64, False) is None


def test_band_reach_cap():
    assert pallas_dia.supported((-(1 << 20), 0), np.float32, False) is None
    assert pallas_dia.choose_tile(1 << 16) == 1 << 16


def test_dispatch_interpret_mode(rng, monkeypatch):
    # csr dot routes through the pallas kernel when forced to interpret
    # mode, and matches the XLA path bit-for-bit on the same input.
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIA", "interpret")
    n = 1024
    A, A_sp = _banded(n, [-1, 0, 1], rng)
    x = rng.standard_normal(n).astype(np.float32)
    y = np.asarray(A @ jnp.asarray(x))
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIA", "0")
    A2 = sparse.csr_array(A_sp)
    y_xla = np.asarray(A2 @ jnp.asarray(x))
    np.testing.assert_allclose(y, y_xla, rtol=1e-6, atol=1e-6)


# ---------------- SpMM (dense multi-RHS) variant ----------------

def _spmm_via_pallas(A, X):
    dia = A._get_dia()
    assert dia is not None
    dia_data, offsets, mask = dia
    packed = pallas_dia.pack_band(dia_data, offsets, A.shape, mask=mask)
    assert packed is not None
    tile = pallas_dia._spmm_tile(packed, X.shape[1])
    assert tile is not None
    return np.asarray(
        pallas_dia.pallas_dia_spmm(
            packed.rdata, packed.rmask, jnp.asarray(X), packed.offsets,
            packed.shape, tile, interpret=True,
        )
    )


@pytest.mark.parametrize("k", [1, 3, 32])
def test_spmm_exact_band(k, rng):
    n = 700
    A, A_sp = _banded(n, [-2, 0, 1], rng)
    X = rng.standard_normal((n, k)).astype(np.float32)
    Y = _spmm_via_pallas(A, X)
    np.testing.assert_allclose(Y, A_sp @ X, rtol=2e-5, atol=2e-5)


def test_spmm_holey_band_mask(rng):
    n = 400
    main = rng.standard_normal(n).astype(np.float32)
    off1 = rng.standard_normal(n - 1).astype(np.float32)
    off1[::5] = 0.0
    A_sp = scsp.diags([main, off1], [0, 1], format="csr")
    A_sp.eliminate_zeros()
    A = sparse.csr_array(A_sp)
    assert A._get_dia()[2] is not None
    X = rng.standard_normal((n, 4)).astype(np.float32)
    Y = _spmm_via_pallas(A, X)
    np.testing.assert_allclose(Y, A_sp @ X, rtol=2e-5, atol=2e-5)


def test_spmm_large_offsets(rng):
    n = 4096
    A, A_sp = _banded(n, [-1100, 0, 1100], rng)
    X = rng.standard_normal((n, 8)).astype(np.float32)
    Y = _spmm_via_pallas(A, X)
    np.testing.assert_allclose(Y, A_sp @ X, rtol=2e-5, atol=2e-5)


def test_spmm_dispatch_interpret(rng, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIA", "interpret")
    n = 512
    A, A_sp = _banded(n, [-1, 0, 1], rng)
    X = rng.standard_normal((n, 5)).astype(np.float32)
    Y = np.asarray(A @ jnp.asarray(X))
    np.testing.assert_allclose(Y, A_sp @ X, rtol=2e-5, atol=2e-5)


# ---------------- banded SpGEMM variant ----------------

def _exact_band(n, offsets, rng, m=None):
    """Band with every in-bounds slot explicit (no holes)."""
    m = n if m is None else m
    diags = []
    for o in offsets:
        vals = rng.standard_normal(max(n, m)).astype(np.float32)
        vals[vals == 0] = 1.0
        diags.append(vals)
    A_sp = scsp.diags(diags, offsets, shape=(n, m), format="csr",
                      dtype=np.float32)
    return sparse.csr_array(A_sp), A_sp


def _spgemm_via_pallas(A, B):
    from legate_sparse_tpu.ops.dia_ops import band_product_offsets

    da, db = A._get_dia(), B._get_dia()
    assert da is not None and da[2] is None
    assert db is not None and db[2] is None
    offs_c = band_product_offsets(da[1], db[1])
    tile = pallas_dia._spgemm_tile(db[1], len(da[1]), len(db[1]),
                                   len(offs_c), da[0].dtype)
    assert tile is not None
    return np.asarray(
        pallas_dia.pallas_dia_spgemm(
            da[0], db[0], da[1], db[1], offs_c, A.shape, B.shape,
            tile, interpret=True,
        )
    ), offs_c


def _dense_from_band(Cd, offs_c, shape):
    out = np.zeros(shape)
    m, n = shape
    for d, o in enumerate(offs_c):
        for j in range(max(0, o), min(n, m + o)):
            out[j - o, j] = Cd[d, j]
    return out


@pytest.mark.parametrize("offsets", [(-1, 0, 1), (-3, 0, 2), (0,)])
def test_spgemm_band_matches_scipy(offsets, rng):
    n = 500
    A, A_sp = _exact_band(n, list(offsets), rng)
    B, B_sp = _exact_band(n, [-2, 0, 1], rng)
    Cd, offs_c = _spgemm_via_pallas(A, B)
    C_ref = (A_sp @ B_sp).toarray()
    np.testing.assert_allclose(_dense_from_band(Cd, offs_c, (n, n)),
                               C_ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_spgemm_band_large_offsets(rng):
    n = 4096
    A, A_sp = _exact_band(n, [-640, 0, 640], rng)
    B, B_sp = _exact_band(n, [-640, 0, 640], rng)
    Cd, offs_c = _spgemm_via_pallas(A, B)
    C_ref = (A_sp @ B_sp).toarray()
    np.testing.assert_allclose(_dense_from_band(Cd, offs_c, (n, n)),
                               C_ref, rtol=2e-4, atol=2e-4)


def test_spgemm_band_rectangular(rng):
    A, A_sp = _exact_band(300, [-1, 0], rng, m=400)
    B, B_sp = _exact_band(400, [0, 2], rng, m=350)
    Cd, offs_c = _spgemm_via_pallas(A, B)
    C_ref = (A_sp @ B_sp).toarray()
    np.testing.assert_allclose(
        _dense_from_band(Cd, offs_c, (300, 350)), C_ref,
        rtol=2e-4, atol=2e-4)


def test_spgemm_dispatch_interpret(rng, monkeypatch):
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIA", "interpret")
    n = 600
    A, A_sp = _exact_band(n, [-1, 0, 1], rng)
    C = A @ A
    C_ref = (A_sp @ A_sp).tocsr()
    np.testing.assert_allclose(C.toscipy().toarray(), C_ref.toarray(),
                               rtol=2e-4, atol=2e-4)


def test_dia_array_dispatch_interpret(rng, monkeypatch):
    # dia_array.dot routes through the pallas kernel too (same
    # dispatch as csr's banded path).
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_DIA", "interpret")
    n = 800
    data = rng.standard_normal((3, n)).astype(np.float32)
    A = sparse.dia_array((jnp.asarray(data), jnp.asarray([-1, 0, 2])),
                         shape=(n, n))
    A_sp = scsp.dia_array((data, [-1, 0, 2]), shape=(n, n))
    x = rng.standard_normal(n).astype(np.float32)
    y = np.asarray(A @ jnp.asarray(x))
    np.testing.assert_allclose(y, A_sp @ x, rtol=2e-5, atol=2e-5)
    assert A._pack not in (None, False)
    X = rng.standard_normal((n, 5)).astype(np.float32)
    Y = np.asarray(A @ jnp.asarray(X))
    np.testing.assert_allclose(Y, A_sp @ X, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("shape", [(64, 64), (5000, 5000), (300, 500),
                                   (500, 300)])
def test_distinct_inputs_mode_matches_aliased(shape, rng, monkeypatch):
    # LEGATE_SPARSE_TPU_PALLAS_INPUTS=distinct replaces the three
    # aliased x operands + clamped index maps with tile-shifted copies
    # and plain maps (the fault-isolation rung).  Semantics must be
    # identical, including the zero edge tiles at the first/last grid
    # steps and rectangular clamping.
    n, m = shape
    offsets = (-5, -1, 0, 1, 5)
    A, A_sp = _banded(n, offsets, rng, m=m)
    x = rng.standard_normal(m).astype(np.float32)
    ref = _spmv_via_pallas(A, x)
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_INPUTS", "distinct")
    # Env is read at trace time: a fresh shape/flag combination would
    # hit the jit cache keyed only on shapes.  Clear to force retrace.
    pallas_dia.pallas_dia_spmv.clear_cache()
    try:
        got = _spmv_via_pallas(A, x)
    finally:
        monkeypatch.undo()
        pallas_dia.pallas_dia_spmv.clear_cache()
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(ref, A_sp @ x, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("tile", [2048, 8192])
def test_tile_override_matches_default(tile, rng, monkeypatch):
    # LEGATE_SPARSE_TPU_PALLAS_TILE changes the grid length (fault
    # isolation) and VMEM working set (tuning); results must be
    # identical to the default tile.
    n = 1 << 13
    offsets = (-5, -1, 0, 1, 5)
    A, A_sp = _banded(n, offsets, rng)
    x = rng.standard_normal(n).astype(np.float32)
    ref = _spmv_via_pallas(A, x)
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_TILE", str(tile))
    dia_data, offs, mask = A._get_dia()
    packed = pallas_dia.pack_band(dia_data, offs, A.shape, mask=mask)
    assert packed is not None and packed.tile == tile
    got = np.asarray(pallas_dia.pallas_dia_spmv(
        packed.rdata, packed.rmask, jnp.asarray(x), packed.offsets,
        packed.shape, packed.tile, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_tile_override_ignored_when_too_small(rng, monkeypatch):
    # An override below the band reach must not break the kernel: the
    # auto-grown tile wins.
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_TILE", "1024")
    assert pallas_dia.choose_tile(5000) == pallas_dia.TILE_MIN


def test_tile_override_over_vmem_budget_degrades_to_auto(monkeypatch):
    # A forced tile that blows the VMEM budget must degrade to the
    # auto tile with a warning, not silently disable the kernel.
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_TILE",
                       str(pallas_dia.TILE_MAX))
    offsets = tuple(range(-100, 101))       # 201 diagonals
    tile = pallas_dia.supported(offsets, np.float32, masked=True)
    assert tile == pallas_dia.TILE_MIN


def test_distinct_inputs_spmm_and_spgemm_match(rng, monkeypatch):
    # The de-aliased input mode now covers the SpMM and banded-SpGEMM
    # kernels too (no XLA fallback under the shift3 variant).
    n = 3000
    offsets = (-5, -1, 0, 1, 5)
    A, A_sp = _banded(n, offsets, rng)
    X = rng.standard_normal((n, 4)).astype(np.float32)

    dd, offs, mask = A._get_dia()
    packed = pallas_dia.pack_band(dd, offs, A.shape, mask=mask)
    tile = pallas_dia._spmm_tile(packed, 4)
    ref_mm = np.asarray(pallas_dia.pallas_dia_spmm(
        packed.rdata, packed.rmask, jnp.asarray(X), packed.offsets,
        packed.shape, tile, interpret=True))

    offs_c = tuple(sorted({a + b for a in offs for b in offs}))
    sg_tile = pallas_dia._spgemm_tile(offs, len(offs), len(offs),
                                      len(offs_c), dd.dtype)
    ref_gg = np.asarray(pallas_dia.pallas_dia_spgemm(
        dd, dd, offs, offs, offs_c, A.shape, A.shape, sg_tile,
        interpret=True))

    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_INPUTS", "distinct")
    pallas_dia.pallas_dia_spmm.clear_cache()
    pallas_dia.pallas_dia_spgemm.clear_cache()
    try:
        got_mm = np.asarray(pallas_dia.pallas_dia_spmm(
            packed.rdata, packed.rmask, jnp.asarray(X), packed.offsets,
            packed.shape, tile, interpret=True))
        got_gg = np.asarray(pallas_dia.pallas_dia_spgemm(
            dd, dd, offs, offs, offs_c, A.shape, A.shape, sg_tile,
            interpret=True))
    finally:
        monkeypatch.undo()
        pallas_dia.pallas_dia_spmm.clear_cache()
        pallas_dia.pallas_dia_spgemm.clear_cache()
    np.testing.assert_allclose(got_mm, ref_mm, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_gg, ref_gg, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ref_mm, A_sp @ X, rtol=1e-4, atol=1e-4)
