# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Pallas ELL SpMV kernel, differentially tested in interpret mode on
the CPU suite (compiles natively on TPU via the same code path)."""

import numpy as np
import pytest

import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu.ops.pallas_spmv import (
    ell_spmv_maybe_pallas, pallas_ell_spmv, TILE_R,
)
from legate_sparse_tpu.ops.spmv import ell_pack


def _banded(n, dtype=np.float32):
    return sparse.diags(
        [np.full(n - 1, -1.0), np.full(n, 2.5), np.full(n - 1, -1.0)],
        [-1, 0, 1], shape=(n, n), format="csr", dtype=dtype,
    )


@pytest.mark.parametrize("n", [TILE_R, TILE_R * 3, 1000])
def test_pallas_ell_spmv_matches_xla(n):
    A = _banded(n)
    x = np.linspace(-1.0, 1.0, n).astype(np.float32)
    W = int(np.diff(np.asarray(A.indptr)).max())
    ed, ec, cnt = ell_pack(A.data, A.indices, A.indptr, n, W)
    rows_p = -(-n // TILE_R) * TILE_R
    pad = rows_p - n
    if pad:
        ed = jnp.concatenate([ed, jnp.zeros((pad, W), ed.dtype)])
        ec = jnp.concatenate([ec, jnp.zeros((pad, W), ec.dtype)])
        cnt = jnp.concatenate([cnt, jnp.zeros((pad,), cnt.dtype)])
    y = np.asarray(
        pallas_ell_spmv(ed, ec, cnt, jnp.asarray(x), interpret=True)
    )[:n]
    np.testing.assert_allclose(y, A.toscipy() @ x, rtol=1e-6, atol=1e-6)


def test_pallas_route_env_gated(monkeypatch):
    n = 300
    A = _banded(n)
    x = np.ones(n, dtype=np.float32)
    monkeypatch.delenv("LEGATE_SPARSE_TPU_PALLAS", raising=False)
    W = int(np.diff(np.asarray(A.indptr)).max())
    ed, ec, cnt = ell_pack(A.data, A.indices, A.indptr, n, W)
    assert ell_spmv_maybe_pallas(ed, ec, cnt, jnp.asarray(x)) is None

    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS", "1")
    y = ell_spmv_maybe_pallas(ed, ec, cnt, jnp.asarray(x))
    assert y is not None
    np.testing.assert_allclose(np.asarray(y), A.toscipy() @ x,
                               rtol=1e-6, atol=1e-6)

    # End-to-end through the matmul dispatch.
    y2 = np.asarray(A @ jnp.asarray(x))
    np.testing.assert_allclose(y2, A.toscipy() @ x, rtol=1e-6, atol=1e-6)


def test_pallas_nonfinite_masking():
    """Padded slots must stay exact zeros against non-finite x."""
    n = 64
    A = _banded(n)
    x = np.ones(n, dtype=np.float32)
    x[-1] = np.inf
    W = int(np.diff(np.asarray(A.indptr)).max())
    ed, ec, cnt = ell_pack(A.data, A.indices, A.indptr, n, W)
    rows_p = TILE_R
    pad = rows_p - n
    ed = jnp.concatenate([ed, jnp.zeros((pad, W), ed.dtype)])
    ec = jnp.concatenate([ec, jnp.zeros((pad, W), ec.dtype)])
    cnt = jnp.concatenate([cnt, jnp.zeros((pad,), cnt.dtype)])
    y = np.asarray(
        pallas_ell_spmv(ed, ec, cnt, jnp.asarray(x), interpret=True)
    )[:n]
    assert np.all(np.isinf(y[-2:]))
    assert np.all(np.isfinite(y[:-2]))
