# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Closed-loop elastic placement (ISSUE 19, docs/PLACEMENT.md).

The subsystem's load-bearing contracts, each pinned here:

- **off == inert**: with ``LEGATE_SPARSE_TPU_PLACEMENT`` unset the
  armed-gateway serving path is bit-for-bit the pre-placement path,
  no ``placement.*`` counter ever moves, ``step()`` returns ``None``
  and the watchdog refuses to start;
- **submesh invariants**: deterministic contiguous disjoint carves,
  ``mesh_fingerprint``-stable rebuilds (the dist-plan ledger and the
  cached reshard permute programs survive controller epochs);
- **propose() purity**: a pure function of its snapshot — known
  values pinned the same way ``capacity.recommend``'s purity is
  pinned in tests/test_attrib.py, plus a source-level no-clock/
  no-counter/no-settings guard;
- **amortization + hysteresis**: hold reasons (steady / no_demand /
  unamortized / cooldown), burning and shrink overrides, thrash
  detection;
- **live migration**: priced == measured ``comm.dist_reshard.*``
  bytes exactly, atomic version swap with old handles draining;
- **the acceptance drill**: a two-tenant skewed load with a burning
  interactive SLO migrates the noisy tenant onto its own submesh,
  measured bytes within 1% of the priced prediction, and the
  post-migration burn drops below the breach threshold;
- **chaos**: the drill's migration-mid-storm scenario holds
  exactly-once / exact-pricing / bitwise-parity invariants.
"""

import inspect
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import legate_sparse_tpu as lst
from legate_sparse_tpu import obs, placement, resilience
from legate_sparse_tpu.engine import Engine, Gateway
from legate_sparse_tpu.obs import (
    capacity, context, counters, report as obs_report, slo, trace,
)
from legate_sparse_tpu.parallel.dist_csr import mesh_fingerprint
from legate_sparse_tpu.placement import (
    PlacementController, PlacementSnapshot, propose,
)
from legate_sparse_tpu.placement import controller as pctl
from legate_sparse_tpu.placement import migrate as pmig
from legate_sparse_tpu.placement import submesh as psub
from legate_sparse_tpu.resilience import chaos
from legate_sparse_tpu.resilience import faults as rfaults
from legate_sparse_tpu.resilience import policy as rpolicy
from legate_sparse_tpu.resilience.outcomes import Rejected
from legate_sparse_tpu.settings import settings

from utils_test.tools import load_tool as _tool

R = len(jax.devices())
DEVS = list(jax.devices())
needs_mesh = pytest.mark.skipif(R < 2, reason="needs >= 2 devices")
needs_grid = pytest.mark.skipif(R < 4, reason="needs >= 4 devices")

_ENG = Engine()


@pytest.fixture(autouse=True)
def _isolation():
    was = trace.enabled()
    obs.reset_all()
    trace.disable()
    context.reset_ids()
    placement.reset()
    yield
    placement.reset()
    obs.reset_all()
    context.reset_ids()
    if was:
        trace.enable()
    else:
        trace.disable()


@pytest.fixture
def placement_on():
    saved = (settings.placement, settings.placement_cooldown_ms,
             settings.placement_watchdog_ms, settings.placement_amortize,
             settings.placement_bw_gbps)
    settings.placement = True
    yield settings
    (settings.placement, settings.placement_cooldown_ms,
     settings.placement_watchdog_ms, settings.placement_amortize,
     settings.placement_bw_gbps) = saved


@pytest.fixture
def gw_on():
    saved = settings.gateway
    settings.gateway = True
    yield settings
    settings.gateway = saved


_RESIL_KNOBS = (
    "resil", "resil_retries", "resil_backoff_ms", "resil_breaker_k",
    "resil_breaker_cooldown_ms",
)


@pytest.fixture
def armed(gw_on):
    """Gateway + resilience armed (the chaos-drill configuration)."""
    saved = {k: getattr(settings, k) for k in _RESIL_KNOBS}
    settings.resil = True
    settings.resil_backoff_ms = 0.0
    resilience.reset()
    yield settings
    for k, v in saved.items():
        setattr(settings, k, v)
    resilience.reset()


@pytest.fixture
def sensors_on():
    """Attribution + SLO evaluator armed (the controller's sensors)."""
    saved = (settings.obs_attrib, settings.obs_slo)
    settings.obs_attrib = True
    settings.obs_slo = True
    yield settings
    settings.obs_attrib, settings.obs_slo = saved


def _random_csr(n=400, density=0.03, seed=0):
    """Engine-eligible square CSR (no DIA/BSR structure to decline
    to) — the un-placed control tenant's matrix."""
    import scipy.sparse as sp

    S = sp.random(n, n, density=density, format="csr",
                  random_state=np.random.default_rng(seed),
                  dtype=np.float32)
    return lst.csr_array(S)


def _tridiag(n=256):
    return lst.diags(
        [np.full(n, 4.0, np.float32), np.full(n - 1, -1.0, np.float32),
         np.full(n - 1, -1.0, np.float32)],
        [0, 1, -1], format="csr", dtype=np.float32)


def _x(n, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(n).astype(np.float32))


def _gateway(**kw):
    base = dict(max_batch=64, queue_depth=128, tenant_quota=64,
                rate=0.0, burst=64.0, slack_ms=1.0, timeout_ms=0.0)
    base.update(kw)
    return Gateway(_ENG, **base)


def _delta(c0, c1, name):
    return int(c1.get(name, 0)) - int(c0.get(name, 0))


def _snap(**kw):
    base = dict(demand={}, qos_weights={}, burns={}, devices=R,
                current={}, payload_bytes={}, shrink=())
    base.update(kw)
    return PlacementSnapshot(**base)


# ---------------------------------------------------------------------------
# off-by-default contract
# ---------------------------------------------------------------------------
def test_placement_off_is_bit_for_bit_and_counter_inert(gw_on):
    """The acceptance inertness clause: with the flag unset the armed
    gateway serves exactly the pre-placement path (inline dispatch for
    placed-shape traffic == plain ``A.dot``), no ``placement.*``
    counter moves, the controller declines to step and the watchdog
    refuses to start."""
    assert settings.placement is False, \
        "suite must run with PLACEMENT unset"
    A = _tridiag(200)
    xs = [_x(200, seed=s) for s in range(4)]
    gw = _gateway()
    c0 = counters.snapshot("placement.")
    try:
        futs = [gw.submit(A, x, tenant="t0", qos="interactive")
                for x in xs]
        gw.flush()
        for x, fut in zip(xs, futs):
            got = np.asarray(fut.result(timeout=30))
            ref = np.asarray(_ENG.matvec(A, x, _checked=True))
            assert (np.array_equal(got, ref)
                    or np.array_equal(got, np.asarray(A.dot(x))))
    finally:
        gw.shutdown()
    assert counters.snapshot("placement.") == c0 == {}
    ctl = PlacementController(devices=DEVS)
    assert ctl.step() is None
    assert ctl.start_watchdog(interval_ms=5) is False
    assert counters.snapshot("placement.") == {}


# ---------------------------------------------------------------------------
# submesh invariants
# ---------------------------------------------------------------------------
def test_feasible_allocation_trims_deterministically():
    rec = {"tenants": {"a": {"devices": 6, "share": 0.7},
                       "b": {"devices": 3, "share": 0.2},
                       "c": {"devices": 1, "share": 0.1}}}
    alloc = psub.feasible_allocation(rec, 8)
    assert alloc == {"a": 4, "b": 3, "c": 1}
    assert psub.feasible_allocation(rec, 8) == alloc  # deterministic
    # Everyone at 1 and still over budget: smallest shares drop out.
    rec2 = {"tenants": {t: {"devices": 1, "share": s}
                        for t, s in (("a", 0.5), ("b", 0.3),
                                     ("c", 0.2))}}
    assert psub.feasible_allocation(rec2, 2) == {"a": 1, "b": 1}


def test_carve_contiguous_disjoint_sorted():
    alloc = {"b": 3, "a": 2, "c": 1}
    slices = psub.carve(alloc, 8)
    assert slices == {"a": (0, 2), "b": (2, 3), "c": (5, 1)}
    assert psub.carve(dict(alloc), 8) == slices   # order-insensitive
    # Contiguity + disjointness: sorted starts tile a prefix.
    spans = sorted(slices.values())
    cursor = 0
    for start, count in spans:
        assert start == cursor
        cursor += count
    assert cursor <= 8
    with pytest.raises(ValueError, match="feasible_allocation"):
        psub.carve({"a": 9}, 8)


@needs_mesh
def test_build_submesh_fingerprint_stable():
    """Invariant 2: equal slices over equal device lists rebuild
    meshes with equal ``mesh_fingerprint``s — the key the dist-plan
    ledger and the reshard permute-program cache survive on."""
    m1 = psub.build_submesh(DEVS, 0, 2)
    m2 = psub.build_submesh(DEVS, 0, 2)
    assert mesh_fingerprint(m1) == mesh_fingerprint(m2)
    if R >= 3:
        m3 = psub.build_submesh(DEVS, 1, 2)
        assert mesh_fingerprint(m3) != mesh_fingerprint(m1)
    assert psub.build_submesh(DEVS, 0, 1) is None
    with pytest.raises(ValueError, match="falls off"):
        psub.build_submesh(DEVS, R - 1, 2)


def test_price_migration_is_the_reshard_predictor():
    from legate_sparse_tpu.obs import comm as obs_comm

    vols = psub.price_migration(1000, 7)
    chunk = -(-1000 // 7)
    assert vols == obs_comm.reshard_volumes(
        moved_chunks=7, chunk_elems=chunk, itemsize=1, shards=7)
    assert psub.priced_bytes(vols) == 7 * chunk
    # Single-device destination still crosses the wire (shards >= 2).
    assert psub.priced_bytes(psub.price_migration(1000, 1)) == 1000
    assert psub.price_migration(0, 4) == {}
    A = _tridiag(64)
    assert psub.payload_bytes(A) == sum(
        np.asarray(p).nbytes for p in (A.data, A.indices, A.indptr))


# ---------------------------------------------------------------------------
# propose(): purity + decision logic
# ---------------------------------------------------------------------------
def test_propose_is_pure_and_deterministic():
    """ISSUE 19 satellite: ``propose`` is a pure function of its
    snapshot — known values pinned like ``recommend``'s purity in
    tests/test_attrib.py, no clock/counter/settings reads inside."""
    snap = _snap(
        demand={"noisy": {"busy_ns": 8_000_000_000,
                          "qos": "interactive"},
                "quiet": {"busy_ns": 1_000_000_000,
                          "qos": "background"}},
        qos_weights={"interactive": 8.0, "background": 1.0},
        burns={"interactive": 1000.0}, devices=8,
        payload_bytes={"noisy": 1000, "quiet": 1000})
    d1 = propose(snap)
    d2 = propose(snap)
    assert d1 == d2
    assert d1.act is True and d1.reason == "burning"
    assert d1.allocation == {"noisy": 7, "quiet": 1}
    assert d1.slices == {"noisy": (0, 7), "quiet": (7, 1)}
    assert d1.moves == d1.slices
    chunk = -(-1000 // 7)
    assert d1.priced_bytes == {"noisy": 7 * chunk, "quiet": 1000}
    assert d1.total_priced_bytes == 7 * chunk + 1000
    # eff_src = fair_share(8, 2) = 4 -> saving 8e9 * (1 - 4/7).
    assert d1.predicted_saving_ns == pytest.approx(
        8e9 * (1 - 4 / 7))
    assert d1.priced_cost_ns == pytest.approx(
        d1.total_priced_bytes / 10.0)
    # No counter movement, and a source-level purity guard: the
    # function body reads no clock, no counters, no settings.
    c0 = counters.snapshot("")
    propose(snap)
    assert counters.snapshot("") == c0
    src = inspect.getsource(pctl.propose)
    for banned in ("time.", "_counters", "_rsettings", "_trace",
                   "monotonic", "perf_counter"):
        assert banned not in src, banned


def test_propose_hold_and_override_reasons():
    base = dict(
        demand={"t": {"busy_ns": 1_000, "qos": "interactive"}},
        qos_weights={"interactive": 8.0}, devices=R)
    # No demand, nothing placed: nothing to decide.
    d = propose(_snap())
    assert (d.act, d.reason) == (False, "no_demand")
    # Demand but nothing registered to move: advisory only.
    d = propose(_snap(**base))
    assert (d.act, d.reason) == (False, "steady")
    assert d.moves == {}
    # A registered tenant with negligible busy time: the priced cost
    # cannot amortize.
    d = propose(_snap(**base, payload_bytes={"t": 10 ** 9}))
    assert (d.act, d.reason) == (False, "unamortized")
    assert d.total_priced_bytes > 0 and d.priced_cost_ns > 0
    # Same move, burning class: the breach already costs more.
    d = propose(_snap(**base, payload_bytes={"t": 10 ** 9},
                      burns={"interactive": pctl.BURN_PAGE}))
    assert (d.act, d.reason) == (True, "burning")
    # Huge dominant demand, tiny payload: the mover grows well past
    # its fair share and efficiency alone amortizes.
    d = propose(_snap(
        demand={"t": {"busy_ns": 10 ** 12, "qos": "interactive"},
                "u": {"busy_ns": 10 ** 10, "qos": "background"}},
        qos_weights={"interactive": 8.0, "background": 1.0}, devices=8,
        payload_bytes={"t": 64}))
    assert (d.act, d.reason) == (True, "amortized")
    assert d.predicted_saving_ns >= d.priced_cost_ns


def test_propose_shrink_halves_flagged_tenant():
    d = propose(_snap(
        devices=8, current={"t": (0, 8)}, payload_bytes={"t": 1000},
        shrink=("t",)))
    assert (d.act, d.reason) == (True, "shrink")
    assert d.allocation["t"] == 4
    assert d.moves == {"t": (0, 4)}
    # Floor 1: a 1-wide slice cannot shrink further, so nothing moves.
    d = propose(_snap(
        devices=8, current={"t": (0, 1)}, payload_bytes={"t": 1000},
        shrink=("t",)))
    assert (d.act, d.reason) == (False, "steady")


def test_propose_keep_your_slice_re_trims():
    """Placed-but-idle tenants keep their slice; when that re-overflows
    the mesh the same deterministic trim applies before carving."""
    d = propose(_snap(
        demand={"a": {"busy_ns": 10 ** 10, "qos": "interactive"}},
        qos_weights={"interactive": 8.0}, devices=8,
        current={"idle": (0, 4)},
        payload_bytes={"a": 1000, "idle": 1000}))
    total = sum(n for n in d.allocation.values())
    assert total <= 8
    assert "idle" in d.allocation and d.allocation["idle"] >= 1


# ---------------------------------------------------------------------------
# registry: place / route / migrate / version drain
# ---------------------------------------------------------------------------
def test_place_requires_square():
    A = lst.csr_array(np.ones((4, 6), np.float32))
    with pytest.raises(ValueError, match="square"):
        placement.place("t", A)
    with pytest.raises(KeyError, match="not placed"):
        placement.migrate_to("ghost", 2, DEVS)


@needs_mesh
def test_migration_priced_equals_measured_and_swaps_version():
    A = _tridiag(256)
    x = _x(256, seed=3)
    placement.place("pt", A)
    reg = placement.registry()
    h0 = placement.route(A, "pt")
    assert placement.is_placed_handle(h0) and h0.version == 0
    assert h0._dist is None
    ref = np.asarray(A.dot(x))
    assert np.array_equal(np.asarray(h0.dot(x)), ref)
    c0 = counters.snapshot("")
    payload = reg.payload_bytes()["pt"]
    moved = placement.migrate_to("pt", R, DEVS)
    c1 = counters.snapshot("")
    priced = psub.priced_bytes(psub.price_migration(payload, R))
    # priced == measured is exact: one predictor on both sides.
    assert moved == priced
    assert _delta(c0, c1, "placement.migrations") == 1
    assert _delta(c0, c1, "placement.migration.bytes") == moved
    assert _delta(c0, c1, "comm.dist_reshard.ppermute_bytes") == moved
    assert _delta(c0, c1, "comm.dist_reshard.ppermute") == 1
    # Atomic swap: new admissions pin v1 on the submesh; the old
    # handle keeps draining on the old placement, bit-for-bit.
    h1 = placement.route(A, "pt")
    assert h1.version == 1 and h1._dist is not None
    assert reg.slices()["pt"] == (0, R)
    assert np.allclose(np.asarray(h1.dot(x)), ref, rtol=1e-5,
                       atol=1e-5)
    assert np.array_equal(np.asarray(h0.dot(x)), ref)
    # Re-placing resets the placement and the version.
    placement.place("pt", A)
    assert reg.version("pt") == 0 and reg.slices() == {}


@needs_mesh
def test_gateway_routes_placed_tenant_inline(gw_on, placement_on):
    A = _random_csr(400)    # engine-eligible: the un-placed tenant's
    x = _x(400, seed=5)     # copy must take the queued path
    placement.place("pt", A)
    gw = _gateway()
    c0 = counters.snapshot("")
    try:
        fut = gw.submit(A, x, tenant="pt", qos="interactive")
        assert fut.done(), "placed traffic serves inline at admission"
        assert np.array_equal(np.asarray(fut.result()),
                              np.asarray(A.dot(x)))
        # Another tenant submitting the same matrix is NOT routed.
        fut2 = gw.submit(A, x, tenant="other", qos="interactive")
        gw.flush()
        fut2.result(timeout=30)
    finally:
        gw.shutdown()
    c1 = counters.snapshot("")
    assert _delta(c0, c1, "placement.routes") == 1
    assert _delta(c0, c1, "gateway.inline") == 1


@needs_grid
def test_breaker_degraded_placed_tenant_shrinks(armed, placement_on):
    """Breaker-degraded mode: a placed tenant keeps serving on its own
    submesh (deferrable class included), gets flagged, and the
    controller's next step halves its slice — cooldown-exempt —
    instead of the gateway shedding globally."""
    A = _tridiag(256)
    x = _x(256, seed=2)
    placement.place("pt", A)
    placement.migrate_to("pt", 4, DEVS)
    br = rpolicy.breaker("gateway.dispatch")
    for _ in range(settings.resil_breaker_k):
        br.record_failure()
    assert br.state == "open"
    gw = _gateway()
    c0 = counters.snapshot("placement.")
    try:
        fut = gw.submit(A, x, tenant="pt", qos="batch")
        assert fut.done()
        out = fut.result()
        assert not isinstance(out, Rejected)
        assert np.allclose(np.asarray(out), np.asarray(A.dot(x)),
                           rtol=1e-5, atol=1e-5)
        # A non-placed deferrable tenant still sheds typed `breaker`.
        B = _tridiag(256)
        shed = gw.submit(B, x, tenant="np", qos="batch").result()
        assert isinstance(shed, Rejected) and shed.reason == "breaker"
        # The flag (and its counter) is idempotent until acted on.
        gw.submit(A, x, tenant="pt", qos="batch").result()
    finally:
        gw.shutdown()
    c1 = counters.snapshot("placement.")
    assert _delta(c0, c1, "placement.degraded_serve") == 2
    assert _delta(c0, c1, "placement.shrink.flagged") == 1
    assert placement.registry().shrink_flagged() == ("pt",)
    ctl = PlacementController(devices=DEVS, cooldown_ms=10 ** 6)
    decision = ctl.step()
    assert decision.act is True and decision.reason == "shrink"
    assert placement.registry().slices()["pt"] == (0, 2)
    assert placement.registry().shrink_flagged() == ()


# ---------------------------------------------------------------------------
# controller: cooldown, hysteresis, thrash, watchdog
# ---------------------------------------------------------------------------
@needs_grid
def test_controller_cooldown_and_thrash(placement_on):
    placement.place("hog", _tridiag(128))
    reg = placement.registry()
    ctl = PlacementController(devices=DEVS, cooldown_ms=1000.0)
    burn = {"interactive": 20.0}
    weights = {"interactive": 8.0}
    snap1 = _snap(
        demand={"hog": {"busy_ns": 8 * 10 ** 9, "qos": "interactive"}},
        qos_weights=weights, burns=burn, devices=R,
        payload_bytes=reg.payload_bytes())
    ctl.snapshot = lambda: snap1
    d1 = ctl.step(now_ns=0)
    assert d1.act is True and d1.reason == "burning"
    assert reg.slices()["hog"] == (0, R)
    # A second burning plan inside the cooldown window is held.
    snap2 = _snap(
        demand={"b": {"busy_ns": 8 * 10 ** 9, "qos": "interactive"},
                "hog": {"busy_ns": 8 * 10 ** 9, "qos": "interactive"}},
        qos_weights=weights, burns=burn, devices=R,
        current=reg.slices(), payload_bytes=reg.payload_bytes())
    ctl.snapshot = lambda: snap2
    d2 = ctl.step(now_ns=500_000_000)
    assert d2.act is False and d2.reason == "cooldown"
    # A shrink bypasses the cooldown; re-migrating the still-burning
    # tenant inside its window is the thrash signature.
    snap3 = snap2._replace(shrink=("hog",))
    ctl.snapshot = lambda: snap3
    d3 = ctl.step(now_ns=600_000_000)
    assert d3.act is True and d3.reason == "shrink"
    c = counters.snapshot("placement.")
    assert c.get("placement.steps") == 3
    assert c.get("placement.proposals") == 3
    assert c.get("placement.migrations") == 2
    assert c.get("placement.hold.cooldown") == 1
    assert c.get("placement.thrash") == 1
    # Outside the window the same plan executes without thrash.
    snap4 = _snap(
        demand={"hog": {"busy_ns": 8 * 10 ** 9, "qos": "interactive"}},
        qos_weights=weights, burns=burn, devices=R,
        current=reg.slices(), payload_bytes=reg.payload_bytes())
    ctl.snapshot = lambda: snap4
    d4 = ctl.step(now_ns=3_000_000_000)
    assert d4.act is True
    assert counters.get("placement.thrash") == 1


def test_controller_watchdog_ticks(placement_on):
    ctl = PlacementController(devices=DEVS, cooldown_ms=10 ** 6)
    ctl.snapshot = lambda: _snap()
    assert ctl.start_watchdog(interval_ms=0) is False
    assert ctl.start_watchdog(interval_ms=5) is True
    assert ctl.start_watchdog(interval_ms=5) is True   # idempotent
    deadline = time.monotonic() + 5.0
    while (counters.get("placement.watchdog.ticks") < 2
           and time.monotonic() < deadline):
        time.sleep(0.01)
    ctl.stop_watchdog()
    assert counters.get("placement.watchdog.ticks") >= 2
    assert counters.get("placement.hold.no_demand") >= 1


# ---------------------------------------------------------------------------
# the acceptance drill: closed loop, SLO-driven migration
# ---------------------------------------------------------------------------
@needs_grid
def test_closed_loop_migration_drops_burn(armed, placement_on,
                                          sensors_on):
    """ISSUE 19 acceptance: two-tenant skewed load with a burning
    interactive SLO -> the controller proposes and executes a
    migration whose measured ``comm.dist_reshard.*`` bytes match the
    priced prediction within 1%, and the noisy tenant's post-migration
    fast-window burn drops below the breach threshold."""
    obs.enable()          # dispatch spans feed the qos attribution
    A1, A2 = _tridiag(256), _tridiag(192)
    placement.place("noisy", A1)
    placement.place("quiet", A2)
    gw = _gateway()
    try:
        # Round 1: a 60ms admission stall on every request blows the
        # 50ms interactive objective for the noisy tenant (background
        # has a 1000ms objective and rides through).
        rfaults.inject("gateway.admit", kind="latency", count=10,
                       latency_ms=60.0)
        for s in range(8):
            gw.submit(A1, _x(256, seed=s), tenant="noisy",
                      qos="interactive").result(timeout=30)
        for s in range(2):
            gw.submit(A2, _x(192, seed=40 + s), tenant="quiet",
                      qos="background").result(timeout=30)
        rfaults.clear()
        verdicts = {v.slo: v for v in slo.evaluate()}
        v1 = verdicts["gateway.interactive"]
        assert v1.status == "breach"
        assert v1.fast_burn >= pctl.BURN_PAGE
        assert counters.get("slo.breach.gateway.interactive") == 1
        # The controller senses the burn + skewed demand and acts.
        ctl = PlacementController(devices=DEVS, cooldown_ms=1000.0)
        c0 = counters.snapshot("comm.dist_reshard.")
        decision = ctl.step()
        c1 = counters.snapshot("comm.dist_reshard.")
        assert decision.act is True and decision.reason == "burning"
        assert "noisy" in decision.moves
        slices = placement.registry().slices()
        assert slices["noisy"][1] >= 2, "the hog got a real submesh"
        measured = _delta(c0, c1, "comm.dist_reshard.ppermute_bytes")
        assert measured > 0
        assert abs(measured - decision.total_priced_bytes) <= \
            0.01 * decision.total_priced_bytes, (
                measured, decision.total_priced_bytes)
        # Warm the new serving path OUTSIDE the measured window (the
        # dist compile is a one-time cost, not steady-state latency),
        # then rebase the fast window on it.
        for s in range(2):
            gw.submit(A1, _x(256, seed=100 + s), tenant="noisy",
                      qos="interactive").result(timeout=60)
        gw.submit(A2, _x(192, seed=120), tenant="quiet",
                  qos="background").result(timeout=60)
        slo.evaluate()      # rebase; the warm compile may breach here
        breaches_warm = counters.get("slo.breach.gateway.interactive")
        # Round 2: same skewed load, no stall, new placement — the
        # burn must fall below the page threshold.
        for s in range(8):
            gw.submit(A1, _x(256, seed=200 + s), tenant="noisy",
                      qos="interactive").result(timeout=60)
        for s in range(2):
            gw.submit(A2, _x(192, seed=240 + s), tenant="quiet",
                      qos="background").result(timeout=60)
        verdicts = {v.slo: v for v in slo.evaluate()}
        v2 = verdicts["gateway.interactive"]
        assert v2.fast_total >= 8
        assert v2.status != "breach"
        assert v2.fast_burn < pctl.BURN_PAGE
        assert counters.get("slo.breach.gateway.interactive") \
            == breaches_warm, "no new breach on the new placement"
    finally:
        rfaults.clear()
        gw.shutdown()
        obs.disable()


# ---------------------------------------------------------------------------
# chaos: migration mid-storm
# ---------------------------------------------------------------------------
def test_chaos_migration_scenario_requires_placement(armed):
    with pytest.raises(RuntimeError, match="settings.placement"):
        chaos.run_drill(None, tenants=[],
                        migration={"tenant": "t", "devices": (2, 4)})


@needs_grid
def test_chaos_drill_migration_mid_storm(armed, placement_on):
    """ISSUE 19 satellite: multi-tenant load with a 0ms-deadline storm
    tenant, a live migration fired mid-round — exactly-once
    resolution, bitwise parity across both placement versions, exact
    ``placement.migration.*`` / ``comm.dist_reshard.*`` accounting
    (asserted inside the scenario; violations land in the report)."""
    A_good = _tridiag(256)
    A_storm = _tridiag(192)
    xs_good = [_x(256, seed=s) for s in range(3)]
    xs_storm = [_x(192, seed=s) for s in range(10, 13)]
    gw = _gateway(max_batch=8)
    c0 = counters.snapshot("")
    try:
        report = chaos.run_drill(
            gw,
            tenants=[
                {"name": "good", "qos": "interactive",
                 "A": A_good, "xs": xs_good},
                {"name": "storm", "qos": "background",
                 "A": A_storm, "xs": xs_storm, "deadline_ms": 0.0},
            ],
            rounds=4, seed=7,
            migration={"tenant": "good", "devices": (2, 4)})
    finally:
        gw.shutdown()
    c1 = counters.snapshot("")
    assert report.ok(), report.violations
    assert report.migrations == 2       # setup carve + mid-storm move
    assert report.submitted == 24
    good = report.per_tenant["good"]
    assert good["submitted"] == good["served"] == 12
    assert good["shed"] == 0 and good["error"] == 0
    storm = report.per_tenant["storm"]
    assert storm["shed"] >= 1, "a 0ms deadline storm must shed"
    assert _delta(c0, c1, "placement.migrations") == 2
    assert _delta(c0, c1, "comm.dist_reshard.ppermute") == 2
    assert placement.registry().slices()["good"] == (0, 4)
    assert not rfaults.armed()


# ---------------------------------------------------------------------------
# ledger rendering + doctor
# ---------------------------------------------------------------------------
def test_render_placement_table():
    assert "placement off" in obs_report.render_placement_table({})
    text = obs_report.render_placement_table({
        "placement.steps": 3, "placement.proposals": 3,
        "placement.hold.cooldown": 1, "placement.migrations": 1,
        "placement.migration.bytes": 1001,
        "comm.dist_reshard.ppermute_bytes": 1001,
        "placement.placed": 2, "placement.routes": 5,
    })
    assert "controller: 3 steps" in text
    assert "migrations: 1 applied" in text
    assert "cooldown" in text and "1001" in text


def test_doctor_migration_thrash_and_disabled_rules():
    doctor = _tool("doctor")
    ev = doctor.Evidence()
    ev.counters = {"placement.thrash": 2}
    finding = next(f for f in doctor.diagnose(ev)
                   if f["code"] == "migration-thrash")
    assert finding["severity"] == "warn"
    assert "2x" in finding["message"]
    assert finding["value"] == "2"
    # A noisy-neighbor burn with NO placement.* counters: the info
    # finding points at the subsystem that would fix it...
    ev.counters = {"attrib.tenant.hog.wall_ns": 9e9,
                   "attrib.tenant.meek.wall_ns": 1e9,
                   "slo.breach.gateway.interactive": 2}
    codes = [f["code"] for f in doctor.diagnose(ev)]
    assert "noisy-neighbor" in codes
    assert "placement-disabled-while-noisy-neighbor" in codes
    # ...and stays quiet once placement is demonstrably live.
    ev.counters["placement.steps"] = 1
    codes = [f["code"] for f in doctor.diagnose(ev)]
    assert "noisy-neighbor" in codes
    assert "placement-disabled-while-noisy-neighbor" not in codes
