# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Block-Jacobi / Jacobi preconditioner factories (precond.py).

Beyond-reference feature (the reference's solvers accept user M only,
``legate_sparse/linalg.py``; scipy's factory is sequential spilu).
"""

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


def _poisson2d(N, eps=1.0):
    """5-point Laplacian, anisotropy ``eps`` on the y-coupling."""
    n = N * N
    main = np.full(n, 2.0 + 2.0 * eps)
    off1 = np.full(n - 1, -1.0)
    off1[np.arange(1, N) * N - 1] = 0.0
    offn = np.full(n - N, -eps)
    mats = ([main, off1, off1, offn, offn], [0, 1, -1, N, -N])
    A = sparse.diags(*mats, shape=(n, n), format="csr", dtype=np.float64)
    A_sp = sp.diags(*mats, format="csr")
    return A, A_sp


def test_block_jacobi_matches_explicit_inverse():
    bs, n = 8, 24
    rng = np.random.default_rng(0)
    R_sp = (sp.random(n, n, density=0.4, format="csr", random_state=rng)
            + 5 * sp.eye(n)).tocsr()
    M = linalg.block_jacobi(sparse.csr_array(R_sp), block_size=bs)
    D = R_sp.toarray()
    v = rng.standard_normal(n)
    want = np.concatenate([
        np.linalg.inv(D[i * bs:(i + 1) * bs, i * bs:(i + 1) * bs])
        @ v[i * bs:(i + 1) * bs] for i in range(n // bs)])
    np.testing.assert_allclose(np.asarray(M.matvec(v)), want, rtol=1e-10)


@pytest.mark.slow
def test_block_jacobi_accelerates_anisotropic_cg():
    # Line blocks along the strong coupling direction: large iteration
    # win on the anisotropic operator.
    N = 48
    A, A_sp = _poisson2d(N, eps=0.01)
    b = np.ones(N * N)
    _, it_plain = linalg.cg(A, b, rtol=1e-8, maxiter=4000,
                            conv_test_iters=5)
    M = linalg.block_jacobi(A, block_size=N)
    x, it_pc = linalg.cg(A, b, M=M, rtol=1e-8, maxiter=4000,
                         conv_test_iters=5)
    assert int(it_pc) < int(it_plain) * 0.5
    assert np.linalg.norm(A_sp @ np.asarray(x) - b) < 1e-5


def test_block_jacobi_ragged_tail_and_scipy_input():
    rng = np.random.default_rng(1)
    R_sp = (sp.random(20, 20, density=0.4, format="csr",
                      random_state=rng) + 5 * sp.eye(20)).tocsr()
    M = linalg.block_jacobi(R_sp, block_size=8)   # 20 = 2*8 + 4 tail
    v = rng.standard_normal(20)
    D = R_sp.toarray()
    want = np.zeros(20)
    for i, lo in enumerate(range(0, 20, 8)):
        hi = min(lo + 8, 20)
        want[lo:hi] = np.linalg.inv(D[lo:hi, lo:hi]) @ v[lo:hi]
    np.testing.assert_allclose(np.asarray(M.matvec(v)), want, rtol=1e-9)


def test_jacobi_and_singular_rejection():
    A, A_sp = _poisson2d(24)
    b = np.ones(24 * 24)
    Mj = linalg.jacobi(A)
    x, _ = linalg.cg(A, b, M=Mj, rtol=1e-8, maxiter=4000,
                     conv_test_iters=5)
    assert np.linalg.norm(A_sp @ np.asarray(x) - b) < 1e-5
    with pytest.raises(ValueError, match="zero on the diagonal"):
        linalg.jacobi(sparse.csr_array(np.array([[0.0, 1], [1, 0]])))
    with pytest.raises(ValueError, match="singular"):
        linalg.block_jacobi(
            sparse.csr_array(np.array([[1.0, 1], [1, 1]])), block_size=2)


def test_block_jacobi_with_minres():
    A, A_sp = _poisson2d(32, eps=0.05)
    b = np.ones(32 * 32)
    M = linalg.block_jacobi(A, block_size=32)
    x, _ = linalg.minres(A, b, M=M, rtol=1e-9, maxiter=4000)
    assert np.linalg.norm(A_sp @ np.asarray(x) - b) < 1e-5


def test_block_jacobi_adjoint_nonsymmetric():
    # rmatvec must apply the per-block conjugate transpose, not M
    # itself (M's diagonal blocks are nonsymmetric here).
    rng = np.random.default_rng(2)
    R_sp = (sp.random(16, 16, density=0.5, format="csr",
                      random_state=rng) + 5 * sp.eye(16)).tocsr()
    M = linalg.block_jacobi(sparse.csr_array(R_sp), block_size=8)
    u = rng.standard_normal(16)
    v = rng.standard_normal(16)
    # <M u, v> == <u, M^H v>
    lhs = np.vdot(np.asarray(M.matvec(u)), v)
    rhs = np.vdot(u, np.asarray(M.rmatvec(v)))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-10)
