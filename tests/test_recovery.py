# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Fault-tolerant distributed solves (ISSUE 15, docs/RESILIENCE.md):
checkpoint/restore at the fetch cadence, the device-loss recovery
ladder (detect -> shrink -> reshard -> restore -> resume), opt-in
ABFT-checksummed dist SpMV, the ``refine=`` deadline-cadence bugfix,
and the off-by-default inertness pins for all of it."""

import numpy as np
import pytest

import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs, resilience
from legate_sparse_tpu.parallel import (
    dist_cg, dist_gmres, dist_spmv, make_row_mesh, shard_csr,
)
from legate_sparse_tpu.parallel.dist_csr import shard_vector
from legate_sparse_tpu.resilience import checkpoint as rckpt
from legate_sparse_tpu.resilience import deadline as rdeadline
from legate_sparse_tpu.resilience import faults as rfaults
from legate_sparse_tpu.settings import settings

_RESIL_KNOBS = (
    "resil", "resil_retries", "resil_backoff_ms", "resil_retry_budget",
    "resil_breaker_k", "resil_breaker_cooldown_ms", "resil_health",
    "resil_ckpt_iters", "resil_abft",
)


@pytest.fixture
def resil():
    saved = {k: getattr(settings, k) for k in _RESIL_KNOBS}
    settings.resil = True
    settings.resil_backoff_ms = 0.0
    resilience.reset()
    obs.counters.reset("resil.")
    yield settings
    for k, v in saved.items():
        setattr(settings, k, v)
    resilience.reset()


def _tridiag(n, dtype=np.float32):
    return sparse.diags(
        [np.full(n, 4.0, dtype), np.full(n - 1, -1.0, dtype),
         np.full(n - 1, -1.0, dtype)],
        [0, 1, -1], format="csr", dtype=dtype)


def _delta(c0, c1, name):
    return int(c1.get(name, 0)) - int(c0.get(name, 0))


def _ref_solve(A, b):
    import scipy.sparse as sp
    import scipy.sparse.linalg as spla

    S = sp.csr_matrix(
        (np.asarray(A.data), np.asarray(A.indices),
         np.asarray(A.indptr)), shape=A.shape)
    return spla.spsolve(S.tocsc(), b)


# ---------------------------------------------------------------------------
# checkpoint: cadence, host buffers, ledger
# ---------------------------------------------------------------------------
def test_checkpoint_rides_cg_fetch_cadence(resil):
    """A checkpoint scope routes the solve through the chunked driver
    and snapshots (x, r, p) every ``every`` iterations at the existing
    conv fetches — no extra host syncs beyond the chunk cadence."""
    A = _tridiag(256)
    b = np.ones(256, np.float32)
    c0 = obs.counters.snapshot()
    with rckpt.scope("t.cg", every=10) as ck:
        x, it = sparse.linalg.cg(A, b, rtol=0.0, maxiter=40,
                                 conv_test_iters=10)
    c1 = obs.counters.snapshot()
    assert int(it) == 40
    assert ck.saves == 4                    # fetches at 10/20/30/40
    assert ck.iterations == 40
    assert len(ck.arrays) == 3              # (x, r, p)
    assert all(isinstance(a, np.ndarray) for a in ck.arrays)
    assert _delta(c0, c1, "resil.ckpt.saves") == 4
    assert _delta(c0, c1, "resil.ckpt.bytes") == 4 * 3 * 256 * 4
    # Snapshots piggyback the chunk fetches (4 chunks = 4 syncs).
    assert _delta(c0, c1, "transfer.host_sync.cg_conv") == 4
    it0, arrays = ck.restore()
    assert it0 == 40
    assert np.array_equal(arrays[0], np.asarray(x))
    assert _delta(c0, obs.counters.snapshot(),
                  "resil.ckpt.restores") == 1


def test_checkpoint_rides_gmres_cycle_cadence(resil):
    A = _tridiag(256)
    b = np.ones(256, np.float32)
    with rckpt.scope("t.gmres", every=10) as ck:
        x, it = sparse.linalg.gmres(A, b, restart=10, rtol=0.0,
                                    maxiter=30)
    assert int(it) == 30
    assert ck.saves == 3                    # one per restart cycle
    assert len(ck.arrays) == 1              # the Arnoldi seed x
    assert np.array_equal(ck.arrays[0], np.asarray(x))


def test_checkpoint_zero_cadence_never_snapshots(resil):
    A = _tridiag(128)
    b = np.ones(128, np.float32)
    with rckpt.scope("t.cg", every=0) as ck:
        sparse.linalg.cg(A, b, maxiter=50)
    assert ck.saves == 0
    assert ck.restore() is None


# ---------------------------------------------------------------------------
# the recovery ladder: detect -> shrink -> reshard -> restore -> resume
# ---------------------------------------------------------------------------
def test_device_loss_recovery_ladder_exact_accounting(resil):
    """The acceptance drill: a seeded device loss mid-``dist_cg`` on
    the 8-virtual-device mesh recovers via mesh-shrink + reshard +
    checkpoint-restore, converging to the same tolerance — with exact
    ``resil.recovery.*`` / ``resil.ckpt.*`` accounting.  Fixed
    iteration plan (rtol=0): fetches at 10/20/30..., snapshot at
    10 and 20, loss at the third fetch, restore from 20, resume the
    remaining 40-iteration budget."""
    n = 256
    A = _tridiag(n)
    dA = shard_csr(A)
    if dA.num_shards < 2:
        pytest.skip("needs >= 2 devices")
    shards0 = int(dA.num_shards)
    b = np.ones(n, np.float32)
    c0 = obs.counters.snapshot()
    rfaults.inject("solver.cg.conv", "device_loss", after=2, device=1)
    with rckpt.scope("dist.cg", every=10):
        x, it = dist_cg(dA, b, rtol=0.0, maxiter=60,
                        conv_test_iters=10)
    c1 = obs.counters.snapshot()
    assert int(it) == 60                    # 20 banked + 40 resumed
    for name, want in (("resil.recovery.attempts", 1),
                       ("resil.recovery.device_loss", 1),
                       ("resil.recovery.mesh_shrink", 1),
                       ("resil.recovery.succeeded", 1),
                       ("resil.recovery.restored_iters", 20),
                       ("resil.ckpt.restores", 1)):
        assert _delta(c0, c1, name) == want, name
    # saves: 2 pre-loss + 4 on the resumed 40-iteration lineage
    assert _delta(c0, c1, "resil.ckpt.saves") == 6
    assert _delta(c0, c1, "resil.recovery.reshard_bytes") > 0
    # Same tolerance as a clean solve of this budget.
    assert np.allclose(np.asarray(x), _ref_solve(A, b),
                       rtol=1e-5, atol=1e-6)
    # The caller's matrix is untouched (the ladder reshards a copy).
    assert int(dA.num_shards) == shards0
    assert rfaults.fired("solver.cg.conv") == 1   # exactly-once
    rfaults.clear()


def test_device_loss_without_snapshot_restarts_from_x0(resil):
    """No snapshot banked yet (cadence off): the ladder restarts from
    the original x0 at iteration 0 — the doctor's
    recovery-without-checkpoint-advance scenario — and still solves."""
    n = 256
    A = _tridiag(n)
    dA = shard_csr(A)
    if dA.num_shards < 2:
        pytest.skip("needs >= 2 devices")
    b = np.ones(n, np.float32)
    c0 = obs.counters.snapshot()
    rfaults.inject("solver.cg.conv", "device_loss", after=0, device=0)
    with rckpt.scope("dist.cg", every=0):
        x, it = dist_cg(dA, b, rtol=0.0, maxiter=40,
                        conv_test_iters=10)
    c1 = obs.counters.snapshot()
    assert int(it) == 40                    # full budget replayed
    assert _delta(c0, c1, "resil.recovery.attempts") == 1
    assert _delta(c0, c1, "resil.ckpt.saves") == 0
    assert _delta(c0, c1, "resil.ckpt.restores") == 0
    assert _delta(c0, c1, "resil.recovery.restored_iters") == 0
    assert np.allclose(np.asarray(x), _ref_solve(A, b),
                       rtol=1e-5, atol=1e-6)


def test_device_loss_recovery_dist_gmres(resil):
    n = 256
    A = _tridiag(n)
    dA = shard_csr(A)
    if dA.num_shards < 2:
        pytest.skip("needs >= 2 devices")
    b = np.ones(n, np.float32)
    c0 = obs.counters.snapshot()
    rfaults.inject("solver.gmres.conv", "device_loss", after=1,
                   device=2)
    with rckpt.scope("dist.gmres", every=10):
        x, it = dist_gmres(dA, b, restart=10, rtol=1e-8, maxiter=100)
    c1 = obs.counters.snapshot()
    assert _delta(c0, c1, "resil.recovery.attempts") == 1
    assert _delta(c0, c1, "resil.ckpt.restores") == 1
    assert _delta(c0, c1, "resil.recovery.restored_iters") == 10
    assert np.allclose(np.asarray(x), _ref_solve(A, b),
                       rtol=1e-4, atol=1e-5)


def test_default_ckpt_cadence_knob_opens_scope(resil):
    """Without an explicit scope, ``settings.resil_ckpt_iters`` > 0
    makes ``dist_cg`` open its own checkpoint scope — the env-knob
    path (LEGATE_SPARSE_TPU_RESIL_CKPT_ITERS) the bench drill uses."""
    n = 256
    A = _tridiag(n)
    dA = shard_csr(A)
    if dA.num_shards < 2:
        pytest.skip("needs >= 2 devices")
    settings.resil_ckpt_iters = 10
    b = np.ones(n, np.float32)
    c0 = obs.counters.snapshot()
    rfaults.inject("solver.cg.conv", "device_loss", after=2, device=1)
    x, it = dist_cg(dA, b, rtol=0.0, maxiter=60, conv_test_iters=10)
    c1 = obs.counters.snapshot()
    assert int(it) == 60
    assert _delta(c0, c1, "resil.ckpt.restores") == 1
    assert _delta(c0, c1, "resil.recovery.restored_iters") == 20


def test_device_loss_on_last_shard_reraises(resil):
    """The ladder is bounded: with no survivor to shrink onto, the
    typed DeviceLost escapes instead of looping."""
    import jax

    A = _tridiag(128)
    dA = shard_csr(A, mesh=make_row_mesh(jax.devices()[:1]))
    b = np.ones(128, np.float32)
    rfaults.inject("solver.cg.conv", "device_loss", after=0, device=0)
    with pytest.raises(resilience.DeviceLost):
        with rckpt.scope("dist.cg", every=10):
            dist_cg(dA, b, rtol=0.0, maxiter=40, conv_test_iters=10)
    assert obs.counters.get("resil.recovery.attempts") == 0
    rfaults.clear()


# ---------------------------------------------------------------------------
# ABFT-checksummed dist SpMV
# ---------------------------------------------------------------------------
def test_abft_clean_pass_counts_checks_only(resil):
    settings.resil_abft = True
    A = _tridiag(256)
    dA = shard_csr(A)
    xv = shard_vector(np.ones(256, np.float32), dA.mesh,
                      dA.rows_padded)
    c0 = obs.counters.snapshot()
    y = np.asarray(dist_spmv(dA, xv))
    c1 = obs.counters.snapshot()
    assert _delta(c0, c1, "resil.abft.checks") == 1
    assert _delta(c0, c1, "resil.abft.mismatch") == 0
    assert np.allclose(y[:256], np.asarray(A @ jnp.ones(256)),
                       rtol=1e-5, atol=1e-6)


def test_abft_mismatch_is_typed_counted_retry(resil):
    """A poisoned collective turns into a ChecksumError the dist.spmv
    retry ladder absorbs: one mismatch, one retry, correct bits."""
    settings.resil_abft = True
    A = _tridiag(256)
    dA = shard_csr(A)
    xv = shard_vector(np.ones(256, np.float32), dA.mesh,
                      dA.rows_padded)
    clean = np.asarray(dist_spmv(dA, xv))
    c0 = obs.counters.snapshot()
    rfaults.inject("dist.spmv.abft", kind="nonfinite", count=1)
    y = np.asarray(dist_spmv(dA, xv))
    c1 = obs.counters.snapshot()
    assert _delta(c0, c1, "resil.abft.mismatch") == 1
    assert _delta(c0, c1, "resil.retry.dist.spmv") == 1
    assert np.array_equal(y, clean)
    rfaults.clear()


def test_abft_exhausted_retries_surface_checksum_error(resil):
    settings.resil_abft = True
    settings.resil_retries = 1
    A = _tridiag(256)
    dA = shard_csr(A)
    xv = shard_vector(np.ones(256, np.float32), dA.mesh,
                      dA.rows_padded)
    rfaults.inject("dist.spmv.abft", kind="nonfinite", count=5)
    with pytest.raises(resilience.ChecksumError):
        dist_spmv(dA, xv)
    rfaults.clear()


def test_abft_off_is_counter_inert(resil):
    assert settings.resil_abft is False
    A = _tridiag(256)
    dA = shard_csr(A)
    xv = shard_vector(np.ones(256, np.float32), dA.mesh,
                      dA.rows_padded)
    c0 = obs.counters.snapshot()
    np.asarray(dist_spmv(dA, xv))
    c1 = obs.counters.snapshot()
    assert _delta(c0, c1, "resil.abft.checks") == 0


# ---------------------------------------------------------------------------
# satellite bugfix: refine= cycles honor the request deadline
# ---------------------------------------------------------------------------
def test_refine_fetch_enforces_deadline_cg(resil):
    """Regression: ``refine=`` cycles bypassed the deadline cadence —
    an expired budget must surface at the refine fetch as a typed
    DeadlineExceeded on the refine site, not run to completion."""
    A = _tridiag(512)
    b = np.ones(512, np.float32)
    with pytest.raises(resilience.DeadlineExceeded) as ei:
        with rdeadline.scope(0.0):
            sparse.linalg.cg(A, b, refine=3, maxiter=500)
    assert ei.value.site == "solver.cg.refine"
    assert ei.value.partial is not None


def test_refine_fetch_enforces_deadline_gmres(resil):
    A = _tridiag(512)
    b = np.ones(512, np.float32)
    with pytest.raises(resilience.DeadlineExceeded) as ei:
        with rdeadline.scope(0.0):
            sparse.linalg.gmres(A, b, refine=3, restart=10,
                                maxiter=500)
    assert ei.value.site == "solver.gmres.refine"


def test_refine_completes_under_generous_deadline(resil):
    A = _tridiag(256)
    b = np.ones(256, np.float32)
    with rdeadline.scope(60_000.0):
        x, it = sparse.linalg.cg(A, b, refine=3, maxiter=500)
    assert np.allclose(np.asarray(x), _ref_solve(A, b),
                       rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# inertness: resil off => bit-for-bit, counter-inert
# ---------------------------------------------------------------------------
def test_resil_off_checkpoint_scope_inert():
    """With LEGATE_SPARSE_TPU_RESIL unset an open checkpoint scope
    changes nothing: no chunked driver, no snapshots, no counters."""
    assert settings.resil is False, "suite must run with RESIL unset"
    A = _tridiag(256)
    b = np.ones(256, np.float32)
    x_plain, it_plain = sparse.linalg.cg(A, b, maxiter=50)
    c0 = obs.counters.snapshot()
    with rckpt.scope("off", every=5) as ck:
        x, it = sparse.linalg.cg(A, b, maxiter=50)
    c1 = obs.counters.snapshot()
    assert ck.saves == 0
    assert int(it) == int(it_plain)
    assert np.array_equal(np.asarray(x), np.asarray(x_plain))
    assert _delta(c0, c1, "resil.ckpt.saves") == 0
    assert _delta(c0, c1, "transfer.host_sync.cg_conv") == 0


def test_resil_off_dist_solves_counter_inert():
    assert settings.resil is False
    n = 256
    A = _tridiag(n)
    dA = shard_csr(A)
    b = np.ones(n, np.float32)
    xv = shard_vector(np.ones(n, np.float32), dA.mesh, dA.rows_padded)
    np.asarray(dist_spmv(dA, xv))          # warm
    c0 = obs.counters.snapshot()
    np.asarray(dist_spmv(dA, xv))
    dist_cg(dA, b, maxiter=50)
    c1 = obs.counters.snapshot()
    moved = {k for k, v in c1.items()
             if v != c0.get(k, 0)
             and (k.startswith("resil.ckpt")
                  or k.startswith("resil.recovery")
                  or k.startswith("resil.abft")
                  or k == "op.reshard")}
    assert not moved, moved
