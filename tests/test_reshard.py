# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Resharding (ISSUE 15, docs/DIST.md): the chunk-permute vector
program and the matrix repartition path.

- every (src, dst) layout pair over {1d-row, 1d-col, 2d-block}
  round-trips through ``reshard`` value-identical to a fresh
  ``shard_csr`` of the source matrix;
- ``reshard_vector`` is ONE cached ppermute whose recorded comm bytes
  match the static ``reshard_volumes`` prediction (1% band — they are
  the same arithmetic, the band guards itemsize/rounding drift);
- the placement fast path and identity pairs ledger zero bytes;
- plan-cache non-aliasing: a resharded matrix's
  ``dist_plan_fingerprint`` never collides with its source's, so the
  engine can never serve a pre-reshard compiled program for it.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from jax.sharding import Mesh

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs
from legate_sparse_tpu.obs import comm as obs_comm
from legate_sparse_tpu.parallel import (
    chunk_permute_plan, dist_cg, dist_plan_fingerprint, dist_spmv,
    make_row_mesh, reshard, reshard_vector, shard_csr,
)
from legate_sparse_tpu.parallel.reshard import (
    _PERMUTE_PROGRAMS,
)
from legate_sparse_tpu.parallel.dist_csr import (
    mesh_fingerprint, shard_vector,
)

LAYOUTS = ("1d-row", "1d-col", "2d-block")


def _tridiag(n, dtype=np.float32):
    return sparse.diags(
        [np.full(n, 4.0, dtype), np.full(n - 1, -1.0, dtype),
         np.full(n - 1, -1.0, dtype)],
        [0, 1, -1], format="csr", dtype=dtype)


def _x(n, seed=1):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(n).astype(np.float32)


def _spmv_ref(A, x):
    return np.asarray(A @ jnp.asarray(x))


def _dist_y(dA, x):
    xv = shard_vector(x, dA.mesh, dA.rows_padded, layout=dA.layout)
    return np.asarray(dist_spmv(dA, xv))[: dA.shape[0]]


def _rotated(mesh: Mesh) -> Mesh:
    devs = list(np.asarray(mesh.devices).reshape(-1))
    return Mesh(np.asarray(devs[1:] + devs[:1]), mesh.axis_names)


# ---------------------------------------------------------------------------
# matrix repartition: the full (src, dst) layout-pair matrix
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("src_layout", LAYOUTS)
@pytest.mark.parametrize("dst_layout", LAYOUTS)
def test_matrix_reshard_pair_matches_fresh_shard(src_layout,
                                                 dst_layout):
    """``reshard(A, layout=dst)`` must be indistinguishable (SpMV
    values, plan fingerprint) from sharding the retained source matrix
    fresh over the destination — for every ordered layout pair."""
    n = 96
    A = _tridiag(n)
    x = _x(n, seed=7)
    ref = _spmv_ref(A, x)
    dA = shard_csr(A, layout=src_layout)
    B = reshard(dA, layout=dst_layout)
    if src_layout == dst_layout:
        assert B is dA, "same-fingerprint reshard must be the fast path"
    fresh = shard_csr(A, mesh=B.mesh, layout=B.layout)
    assert dist_plan_fingerprint(B) == dist_plan_fingerprint(fresh)
    assert np.allclose(_dist_y(B, x), ref, rtol=1e-5, atol=1e-6)
    assert np.allclose(_dist_y(fresh, x), ref, rtol=1e-5, atol=1e-6)
    # And back: the round trip lands on the source fingerprint again.
    C = reshard(B, mesh=dA.mesh, layout=src_layout)
    assert dist_plan_fingerprint(C) == dist_plan_fingerprint(dA)
    assert np.allclose(_dist_y(C, x), ref, rtol=1e-5, atol=1e-6)


def test_matrix_reshard_requires_retained_source():
    A = _tridiag(64)
    dA = shard_csr(A)
    dA2 = shard_csr(A)
    dA2._src_csr = None
    with pytest.raises(ValueError, match="_src_csr"):
        reshard(dA2, layout="2d-block")
    # the retained-source path still serves the sibling
    assert reshard(dA, layout="1d-row") is dA


def test_matrix_reshard_shrink_is_a_repartition():
    """A smaller destination mesh (the recovery ladder's shrink rung)
    repartitions through the retained source and still solves."""
    n = 128
    A = _tridiag(n)
    dA = shard_csr(A)
    if dA.num_shards < 2:
        pytest.skip("needs >= 2 devices")
    devs = list(np.asarray(dA.mesh.devices).reshape(-1))
    small = make_row_mesh(devs[:-1])
    B = reshard(dA, mesh=small)
    assert B.num_shards == dA.num_shards - 1
    b = np.ones(n, np.float32)
    x, _it = dist_cg(B, b, rtol=1e-8, maxiter=300)
    assert np.allclose(_spmv_ref(A, np.asarray(x)[:n]), b,
                       rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# vector chunk-permute program
# ---------------------------------------------------------------------------
def test_vector_chunk_permute_roundtrip_bitwise():
    mesh = make_row_mesh()
    G = int(np.asarray(mesh.devices).size)
    if G < 2:
        pytest.skip("needs >= 2 devices")
    dst = _rotated(mesh)
    n = 64 * G
    v = shard_vector(np.arange(n, dtype=np.float32), mesh, n)
    w = reshard_vector(v, dst)
    # Same global vector, destination placement: chunk c now lives on
    # the device that owns chunk c under the destination mesh.
    assert np.array_equal(np.asarray(w), np.asarray(v))
    dst_devs = list(np.asarray(dst.devices).reshape(-1))
    for s in w.addressable_shards:
        c = int(np.asarray(s.data)[0]) // (n // G)
        assert s.device == dst_devs[c]
    # Round trip back is bitwise the original.
    v2 = reshard_vector(w, mesh)
    assert np.array_equal(np.asarray(v2), np.asarray(v))
    for a, b in zip(v.addressable_shards, v2.addressable_shards):
        assert a.device == b.device


def test_vector_comm_counters_match_static_prediction():
    mesh = make_row_mesh()
    G = int(np.asarray(mesh.devices).size)
    if G < 2:
        pytest.skip("needs >= 2 devices")
    dst = _rotated(mesh)
    n = 64 * G
    v = shard_vector(np.ones(n, np.float32), mesh, n)
    c0 = obs.counters.snapshot("comm.")
    reshard_vector(v, dst)
    c1 = obs.counters.snapshot("comm.")
    predicted = obs_comm.reshard_volumes(
        moved_chunks=G, chunk_elems=n // G, itemsize=4,
        shards=G)["ppermute"]
    recorded = (c1.get("comm.dist_reshard.ppermute_bytes", 0)
                - c0.get("comm.dist_reshard.ppermute_bytes", 0))
    assert recorded > 0
    assert abs(recorded - predicted) <= 0.01 * predicted, (
        recorded, predicted)
    assert (c1.get("comm.dist_reshard.ppermute", 0)
            - c0.get("comm.dist_reshard.ppermute", 0)) == 1
    # The by-layout aggregate slices the same bytes.
    assert (c1.get("comm.layout.1d-row.dist_reshard_bytes", 0)
            - c0.get("comm.layout.1d-row.dist_reshard_bytes", 0)
            ) == recorded


def test_vector_identity_placement_ledgers_zero():
    mesh = make_row_mesh()
    n = 64 * int(np.asarray(mesh.devices).size)
    v = shard_vector(np.ones(n, np.float32), mesh, n)
    c0 = obs.counters.snapshot("comm.")
    w = reshard_vector(v, mesh)
    c1 = obs.counters.snapshot("comm.")
    assert np.array_equal(np.asarray(w), np.asarray(v))
    assert (c1.get("comm.dist_reshard.ppermute_bytes", 0)
            == c0.get("comm.dist_reshard.ppermute_bytes", 0))


def test_vector_program_cached_per_mesh_pair():
    """Equal (src, dst) fingerprint pairs share ONE compiled program
    — including meshes rebuilt from the same devices."""
    mesh = make_row_mesh()
    G = int(np.asarray(mesh.devices).size)
    if G < 2:
        pytest.skip("needs >= 2 devices")
    dst = _rotated(mesh)
    n = 64 * G
    v = shard_vector(np.ones(n, np.float32), mesh, n)
    reshard_vector(v, dst)
    n_programs = len(_PERMUTE_PROGRAMS)
    # Fresh-but-equal mesh objects: cache hit, no new entry.
    mesh2 = make_row_mesh()
    v2 = shard_vector(np.ones(n, np.float32), mesh2, n)
    reshard_vector(v2, _rotated(mesh2))
    assert len(_PERMUTE_PROGRAMS) == n_programs


def test_vector_shrink_rejected_typed():
    mesh = make_row_mesh()
    devs = list(np.asarray(mesh.devices).reshape(-1))
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    n = 64 * len(devs)
    v = shard_vector(np.ones(n, np.float32), mesh, n)
    with pytest.raises(ValueError, match="repartition"):
        reshard_vector(v, make_row_mesh(devs[:-1]))
    with pytest.raises(ValueError, match="same device set"):
        chunk_permute_plan(mesh, make_row_mesh(devs[:-1]))


def test_vector_device_count_error_names_both_fingerprints():
    """ISSUE 19 satellite: the device-count mismatch error reports the
    src AND dst ``mesh_fingerprint`` — the same keys the dist-plan
    ledger and permute-program cache (and the placement controller's
    plans) are indexed by, so a failed migration is debuggable against
    those ledgers."""
    mesh = make_row_mesh()
    devs = list(np.asarray(mesh.devices).reshape(-1))
    if len(devs) < 2:
        pytest.skip("needs >= 2 devices")
    n = 64 * len(devs)
    v = shard_vector(np.ones(n, np.float32), mesh, n)
    dst = make_row_mesh(devs[:-1])
    with pytest.raises(ValueError) as ei:
        reshard_vector(v, dst)
    msg = str(ei.value)
    assert mesh_fingerprint(mesh) in msg
    assert mesh_fingerprint(dst) in msg
    assert f"{len(devs)} -> {len(devs) - 1}" in msg


def test_chunk_permute_plan_pairs():
    mesh = make_row_mesh()
    devs = list(np.asarray(mesh.devices).reshape(-1))
    G = len(devs)
    pairs, moved = chunk_permute_plan(mesh, mesh)
    assert moved == 0
    assert pairs == tuple((c, c) for c in range(G))
    if G < 2:
        return
    pairs, moved = chunk_permute_plan(mesh, _rotated(mesh))
    assert moved == G                       # full rotation: all move
    assert len(pairs) == G


# ---------------------------------------------------------------------------
# plan-cache non-aliasing
# ---------------------------------------------------------------------------
def test_resharded_matrix_never_aliases_source_plans():
    """``dist_plan_fingerprint`` folds ``mesh_fingerprint(mesh,
    layout)``, so any real reshard (layout change, placement change,
    shrink) yields a distinct plan identity — the engine's dist-plan
    cache cannot hand a pre-reshard executable to the new partition."""
    A = _tridiag(96)
    dA = shard_csr(A)
    fp0 = dist_plan_fingerprint(dA)
    B = reshard(dA, layout="2d-block")
    assert dist_plan_fingerprint(B) != fp0
    devs = list(np.asarray(dA.mesh.devices).reshape(-1))
    if len(devs) >= 2:
        rot = reshard(dA, mesh=_rotated(dA.mesh))
        assert dist_plan_fingerprint(rot) != fp0
        assert (mesh_fingerprint(rot.mesh, rot.layout)
                != mesh_fingerprint(dA.mesh, dA.layout))
        small = reshard(dA, mesh=make_row_mesh(devs[:-1]))
        assert dist_plan_fingerprint(small) != fp0
    # The no-op rung keeps the identity (same object, same plans).
    assert dist_plan_fingerprint(reshard(dA)) == fp0
