# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Resilience subsystem drills (ISSUE 5, docs/RESILIENCE.md).

Deterministic fault-injection drills for every instrumented site:
fail-twice-then-succeed must be bit-identical to the no-fault run with
EXACT ``resil.*`` counter accounting; breakers open at K and recover
through the half-open probe; deadlines shed with typed outcomes (never
hangs, never silent NaN); health detection surfaces structured
verdicts; and with ``LEGATE_SPARSE_TPU_RESIL`` unset nothing changes —
pinned through the existing ``trace.*``/``transfer.*`` counters.
Plus the two CI satellites: the static fault-site coverage check and
the executor atexit-drain regression."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

import legate_sparse_tpu as sparse
from legate_sparse_tpu import obs, resilience
from legate_sparse_tpu.resilience import deadline as rdeadline
from legate_sparse_tpu.settings import settings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RESIL_KNOBS = (
    "resil", "resil_retries", "resil_backoff_ms", "resil_backoff_mult",
    "resil_backoff_max_ms", "resil_retry_budget", "resil_breaker_k",
    "resil_breaker_cooldown_ms", "resil_health",
    "resil_stagnation_cycles", "resil_divergence_mult",
)


@pytest.fixture
def resil():
    """Resilience on with fast drills (no real backoff sleeps), full
    state restore + disarm after each test."""
    saved = {k: getattr(settings, k) for k in _RESIL_KNOBS}
    settings.resil = True
    settings.resil_backoff_ms = 0.0
    settings.resil_breaker_cooldown_ms = 40.0
    resilience.reset()
    obs.counters.reset("resil.")
    yield settings
    for k, v in saved.items():
        setattr(settings, k, v)
    resilience.reset()


def _tridiag(n, dtype=np.float32):
    return sparse.diags(
        [np.full(n, 4.0, dtype), np.full(n - 1, -1.0, dtype),
         np.full(n - 1, -1.0, dtype)],
        [0, 1, -1], format="csr", dtype=dtype)


def _rand_csr(n=300, seed=0):
    import scipy.sparse as sp

    S = sp.random(n, n, density=0.04, random_state=seed, format="csr",
                  dtype=np.float32)
    return sparse.csr_array(S)


from utils_test.tools import load_tool as _tool


# ---------------------------------------------------------------------------
# satellite: static fault-site coverage check (CI teeth)
# ---------------------------------------------------------------------------
def test_check_fault_sites_passes(capsys):
    rc = _tool("check_fault_sites").main([])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_check_fault_sites_catches_rot(capsys, monkeypatch):
    """An orphaned catalog entry (site with no call-site literal) must
    fail the pass — that is the rot the tool exists to catch."""
    mod = _tool("check_fault_sites")
    monkeypatch.setitem(mod.CATALOG, "engine.plan.nonexistent_site",
                        "synthetic rot probe")
    rc = mod.main([])
    out = capsys.readouterr()
    assert rc == 1
    assert "nonexistent_site" in out.err


# ---------------------------------------------------------------------------
# inertness: RESIL unset => zero behavior change, no resil.* activity,
# no extra host syncs (trace.*/transfer.* counters)
# ---------------------------------------------------------------------------
def test_inert_when_off():
    assert settings.resil is False, "suite must run with RESIL unset"
    A = _rand_csr(seed=3)
    x = jnp.ones((A.shape[1],), jnp.float32)
    _ = np.asarray(A @ x)                      # warm compile
    before = obs.counters.snapshot()
    y = np.asarray(A @ x)
    b_vec = np.ones(A.shape[0], np.float32)
    At = _tridiag(256)
    _x, _it = sparse.linalg.cg(At, np.ones(256, np.float32),
                               maxiter=50)
    after = obs.counters.snapshot()
    assert not any(k.startswith("resil.") for k, v in after.items()
                   if v != before.get(k, 0)), "resil.* moved while off"
    # No new transfer counters beyond the ops' own contract: the
    # wrapped dot/cg added no host syncs (cg's while_loop path runs —
    # cg_conv is the chunked-driver counter and must stay absent).
    assert after.get("transfer.host_sync.cg_conv", 0) == before.get(
        "transfer.host_sync.cg_conv", 0)
    assert y.shape == (A.shape[0],)


def test_engine_zero_retrace_hit_path_with_resil_on(resil):
    """Resilience on must not perturb the engine's warm path: a
    same-bucket call leaves every trace.* compile counter unchanged
    (the PR 4 zero-retrace pin, re-asserted under the wrapper)."""
    saved = settings.engine
    from legate_sparse_tpu.engine import Engine

    try:
        settings.engine = True
        eng = Engine()
        A1 = _rand_csr(n=400, seed=5)
        A2 = _rand_csr(n=398, seed=6)          # same pow2 buckets
        x1 = jnp.ones((400,), jnp.float32)
        x2 = jnp.ones((398,), jnp.float32)
        y1 = eng.matvec(A1, x1)
        assert y1 is not None
        _ = np.asarray(eng.matvec(A2, x2))     # absorb pack build
        before = {k: v for k, v in obs.counters.snapshot().items()
                  if k.startswith("trace.")}
        _ = np.asarray(eng.matvec(A2, x2))
        after = {k: v for k, v in obs.counters.snapshot().items()
                 if k.startswith("trace.")}
        assert after == before, "warm engine call retraced under resil"
    finally:
        settings.engine = saved


# ---------------------------------------------------------------------------
# tentpole: per-site inject-twice-then-succeed drills — bit-identical
# results, exact counter accounting
# ---------------------------------------------------------------------------
def _drill(site, run_clean, run=None, exact_bits=True):
    """Shared drill body: clean run, arm fail-twice, rerun, compare."""
    run = run or run_clean
    clean = run_clean()
    r0 = obs.counters.get(f"resil.retry.{site}")
    f0 = obs.counters.get(f"resil.fault.{site}.injected")
    resilience.inject(site, kind="error", count=2)
    recovered = run()
    assert obs.counters.get(f"resil.retry.{site}") - r0 == 2
    assert obs.counters.get(f"resil.fault.{site}.injected") - f0 == 2
    assert resilience.faults.fired(site) == 2
    cmp = np.array_equal if exact_bits else np.allclose
    assert cmp(np.asarray(clean), np.asarray(recovered)), site
    resilience.faults.clear()


def test_drill_csr_dot(resil):
    A = _rand_csr(seed=1)
    x = jnp.ones((A.shape[1],), jnp.float32)
    _drill("csr.dot", lambda: A @ x)


def test_drill_engine_dispatch_and_plan_build(resil):
    saved = settings.engine
    from legate_sparse_tpu.engine import Engine, reset_engine

    try:
        settings.engine = True
        reset_engine()
        A = _rand_csr(seed=2)
        x = jnp.ones((A.shape[1],), jnp.float32)
        # Dispatch drill goes through the ROUTED path (A @ x): the
        # engine.exec.dispatch retry policy lives in route_matvec.
        _drill("engine.exec.dispatch", lambda: A @ x)
        # plan build: a FRESH engine so the build really runs (the
        # build-retry policy lives inside the plan cache itself); the
        # clean reference is the warm routed result.
        clean = np.asarray(A @ x)
        resilience.inject("engine.plan.build", kind="error", count=2)
        eng2 = Engine()
        y = np.asarray(eng2.matvec(A, x))
        assert obs.counters.get("resil.retry.engine.plan.build") == 2
        assert np.array_equal(clean, y)
        resilience.faults.clear()
    finally:
        settings.engine = saved
        reset_engine()


def test_drill_executor_queue_degrades_inline(resil):
    """An injected queue fault degrades to inline service: the Future
    still resolves with the correct product."""
    saved = settings.engine
    from legate_sparse_tpu.engine import Engine, RequestExecutor

    try:
        settings.engine = True
        A = _rand_csr(seed=7)
        x = jnp.ones((A.shape[1],), jnp.float32)
        eng = Engine()
        ex = RequestExecutor(eng, max_batch=4, queue_depth=16,
                             timeout_ms=0)
        clean_fut = ex.submit(A, x)
        ex.flush()                  # timeout 0 = flush-only dispatch
        clean = np.asarray(clean_fut.result(timeout=30))
        resilience.inject("engine.exec.queue", kind="error", count=1)
        fut = ex.submit(A, x)       # fault -> served inline, no flush
        y = np.asarray(fut.result(timeout=30))
        assert obs.counters.get("resil.exec.queue_fault_inline") == 1
        assert np.allclose(clean, y)
        ex.shutdown()
        resilience.faults.clear()
    finally:
        settings.engine = saved


def test_drill_solver_gmres(resil):
    A = _tridiag(128)
    b = np.ones(128, np.float32)
    _drill("solver.gmres.conv",
           lambda: sparse.linalg.gmres(A, b, restart=10,
                                       maxiter=100)[0])


def test_drill_solver_cg_chunked(resil):
    # The chunked driver (site solver.cg.conv) engages under an active
    # deadline scope; generous budget so only the fault fires.
    A = _tridiag(256)
    b = np.ones(256, np.float32)

    def run():
        with rdeadline.scope(60_000.0):
            return sparse.linalg.cg(A, b, maxiter=100)[0]

    _drill("solver.cg.conv", run)


def test_chunked_cg_bit_identical_to_plain(resil):
    """The resilience driver itself is bit-for-bit the one-shot
    while_loop: same iterates, same count."""
    A = _tridiag(256)
    b = np.ones(256, np.float32)
    x_plain, it_plain = sparse.linalg.cg(A, b, maxiter=100)
    with rdeadline.scope(60_000.0):
        x_res, it_res = sparse.linalg.cg(A, b, maxiter=100)
    assert int(it_plain) == int(it_res)
    assert np.array_equal(np.asarray(x_plain), np.asarray(x_res))


def test_drill_dist_sites(resil):
    """Dist drills: injected collective failures retry without
    corrupting results — including the issue's dist_cg convergence
    drill."""
    from legate_sparse_tpu.parallel import (
        dist_cg, dist_spgemm, dist_spmv, shard_csr,
    )

    A = _tridiag(256)
    dA = shard_csr(A)
    xv = jnp.ones((dA.rows_padded,), jnp.float32)
    _drill("dist.spmv", lambda: dist_spmv(dA, xv))

    b = np.ones(256, np.float32)
    clean_x, clean_it = dist_cg(dA, b, maxiter=100)
    resilience.inject("dist.cg", kind="error", count=1)
    x1, it1 = dist_cg(dA, b, maxiter=100)
    assert obs.counters.get("resil.retry.dist.cg") == 1
    assert int(clean_it) == int(it1)
    assert np.array_equal(np.asarray(clean_x), np.asarray(x1))
    resilience.faults.clear()

    C0 = dist_spgemm(dA, dA).to_csr()
    resilience.inject("dist.spgemm", kind="error", count=1)
    C1 = dist_spgemm(dA, dA).to_csr()
    assert obs.counters.get("resil.retry.dist.spgemm") == 1
    assert np.array_equal(np.asarray(C0.data), np.asarray(C1.data))
    assert np.array_equal(np.asarray(C0.indices),
                          np.asarray(C1.indices))
    resilience.faults.clear()


def test_fault_point_suppressed_under_trace(resil):
    """``fault_point`` inside an ambient jax trace must not fire (the
    effect would be staged into the compiled program and replayed
    forever): it counts a trace_skip instead."""
    import jax

    from legate_sparse_tpu.resilience import faults

    resilience.inject("csr.dot", kind="error", count=100)

    @jax.jit
    def f(v):
        faults.fault_point("csr.dot")
        return v * 2

    y = np.asarray(f(jnp.ones(4, jnp.float32)))   # no raise at trace
    assert np.array_equal(y, np.full(4, 2.0, np.float32))
    assert obs.counters.get("resil.fault.trace_skipped") >= 1
    assert obs.counters.get("resil.fault.csr.dot.injected") == 0
    resilience.faults.clear()


def test_nested_site_retry_inside_dist_cg(resil):
    """The eager SpMV dispatches inside dist_cg (the r0 residual build)
    carry their own dist.spmv retry ladder, while the traced loop body
    bypasses the wrapper entirely: a fail-twice fault on dist.spmv is
    absorbed below the solver — dist.cg records no retries, and the
    injected count stays at 2 (NOT ~2 per iteration, which is what
    firing inside the traced while_loop body would produce)."""
    from legate_sparse_tpu.parallel import dist_cg, shard_csr

    A = _tridiag(256)
    dA = shard_csr(A)
    b = np.ones(256, np.float32)
    clean_x, clean_it = dist_cg(dA, b, maxiter=100)
    resilience.inject("dist.spmv", kind="error", count=2)
    x, it = dist_cg(dA, b, maxiter=100)
    assert obs.counters.get("resil.fault.dist.spmv.injected") == 2
    assert obs.counters.get("resil.retry.dist.spmv") == 2
    assert obs.counters.get("resil.retry.dist.cg") == 0
    assert int(it) == int(clean_it)
    assert np.array_equal(np.asarray(clean_x), np.asarray(x))
    resilience.faults.clear()


# ---------------------------------------------------------------------------
# breaker: opens at K, half-open probe recovery, engine ladder flip
# ---------------------------------------------------------------------------
def test_breaker_opens_at_k_and_recovers(resil):
    settings.resil_retries = 0
    settings.resil_breaker_k = 3
    A = _rand_csr(seed=4)
    x = jnp.ones((A.shape[1],), jnp.float32)
    resilience.inject("csr.dot", kind="error", count=3)
    for _ in range(2):
        with pytest.raises(resilience.InjectedFault):
            A @ x
    assert resilience.breaker("csr.dot").state == "closed"
    with pytest.raises(resilience.InjectedFault):
        A @ x                                   # K-th consecutive
    assert resilience.breaker("csr.dot").state == "open"
    assert obs.counters.get("resil.breaker.csr.dot.trips") == 1
    # Open: typed fast-fail (csr.dot has no cheaper rung), not a hang
    # and not silent garbage.
    with pytest.raises(resilience.CircuitOpenError):
        A @ x
    assert obs.counters.get("resil.breaker.csr.dot.short_circuit") == 1
    # Cooldown -> half-open -> successful probe closes it.
    time.sleep(settings.resil_breaker_cooldown_ms / 1e3 + 0.01)
    y = np.asarray(A @ x)
    assert resilience.breaker("csr.dot").state == "closed"
    assert obs.counters.get("resil.breaker.close") == 1
    assert y.shape == (A.shape[0],)


def test_breaker_half_open_failure_reopens(resil):
    settings.resil_retries = 0
    settings.resil_breaker_k = 2
    A = _rand_csr(seed=8)
    x = jnp.ones((A.shape[1],), jnp.float32)
    resilience.inject("csr.dot", kind="error", count=3)
    for _ in range(2):
        with pytest.raises(resilience.InjectedFault):
            A @ x
    assert resilience.breaker("csr.dot").state == "open"
    time.sleep(settings.resil_breaker_cooldown_ms / 1e3 + 0.01)
    with pytest.raises(resilience.InjectedFault):
        A @ x                                   # probe fails
    assert resilience.breaker("csr.dot").state == "open"
    assert obs.counters.get("resil.breaker.csr.dot.trips") == 2


def test_breaker_flips_engine_ladder(resil):
    """An open engine.exec.dispatch breaker short-circuits the engine
    rung: A @ x keeps serving through the plain dispatch, and the
    half-open probe restores the engine."""
    saved = settings.engine
    from legate_sparse_tpu.engine import reset_engine

    try:
        settings.engine = False
        A = _rand_csr(seed=9)
        x = jnp.ones((A.shape[1],), jnp.float32)
        y_plain = np.asarray(A @ x)
        settings.engine = True
        reset_engine()
        settings.resil_retries = 0
        settings.resil_breaker_k = 2
        resilience.inject("engine.exec.dispatch", kind="error",
                          count=2)
        for _ in range(2):
            # Retries exhausted (0 allowed) -> fallback -> plain rung:
            # the call still SUCCEEDS with the plain kernel's bits.
            assert np.array_equal(np.asarray(A @ x), y_plain)
        assert resilience.breaker("engine.exec.dispatch").state == \
            "open"
        y = np.asarray(A @ x)                   # short-circuited
        assert np.array_equal(y, y_plain)
        assert obs.counters.get(
            "resil.breaker.engine.exec.dispatch.short_circuit") >= 1
        time.sleep(settings.resil_breaker_cooldown_ms / 1e3 + 0.01)
        y2 = np.asarray(A @ x)                  # probe: engine again
        assert resilience.breaker("engine.exec.dispatch").state == \
            "closed"
        assert np.allclose(y2, y_plain, rtol=1e-5, atol=1e-6)
    finally:
        settings.engine = saved
        reset_engine()


def test_retry_budget_bounds_amplification(resil):
    settings.resil_retries = 5
    settings.resil_retry_budget = 1
    resilience.reset()                          # refill with budget=1
    A = _rand_csr(seed=10)
    x = jnp.ones((A.shape[1],), jnp.float32)
    resilience.inject("csr.dot", kind="error", count=10)
    with pytest.raises(resilience.InjectedFault):
        A @ x
    assert obs.counters.get("resil.retry.csr.dot") == 1
    assert obs.counters.get("resil.retry.budget_exhausted") == 1


# ---------------------------------------------------------------------------
# deadlines: executor shedding + solver typed outcomes
# ---------------------------------------------------------------------------
def test_executor_sheds_expired_at_admission(resil):
    saved = settings.engine
    from legate_sparse_tpu.engine import Engine, RequestExecutor

    try:
        settings.engine = True
        A = _rand_csr(seed=11)
        x = jnp.ones((A.shape[1],), jnp.float32)
        ex = RequestExecutor(Engine(), max_batch=8, queue_depth=64,
                             timeout_ms=0)
        with rdeadline.scope(0.0):
            fut = ex.submit(A, x)
        out = fut.result(timeout=10)
        assert isinstance(out, resilience.Rejected)
        assert out.site == "engine.exec.queue"
        assert out.deadline_ms == 0.0
        assert obs.counters.get("resil.shed.engine.exec.queue") == 1
        ex.shutdown()
    finally:
        settings.engine = saved


def test_executor_sheds_expired_at_flush(resil):
    """Queue wait counts against the deadline: a request that expires
    while queued is shed at flush with its waited_ms recorded, while a
    fresh request in the same batch still dispatches."""
    saved = settings.engine
    from legate_sparse_tpu.engine import Engine, RequestExecutor

    try:
        settings.engine = True
        A = _rand_csr(seed=12)
        x = jnp.ones((A.shape[1],), jnp.float32)
        ex = RequestExecutor(Engine(), max_batch=8, queue_depth=64,
                             timeout_ms=0)
        with rdeadline.scope(30.0):
            doomed = ex.submit(A, x)
        healthy = ex.submit(A, x)               # no deadline scope
        time.sleep(0.05)
        ex.flush()
        out = doomed.result(timeout=10)
        assert isinstance(out, resilience.Rejected)
        assert out.site == "engine.exec.dispatch"
        assert out.waited_ms >= 30.0
        y = np.asarray(healthy.result(timeout=30))
        assert y.shape == (A.shape[0],)
        assert np.all(np.isfinite(y))
        ex.shutdown()
    finally:
        settings.engine = saved


def test_solver_deadline_typed_outcomes(resil):
    A = _tridiag(512)
    b = np.ones(512, np.float32)
    with pytest.raises(resilience.DeadlineExceeded) as ei:
        with rdeadline.scope(0.0):
            sparse.linalg.cg(A, b, maxiter=1000)
    assert ei.value.site == "solver.cg.conv"
    assert ei.value.iterations == 0             # shed before dispatch
    with pytest.raises(resilience.DeadlineExceeded) as ei:
        with rdeadline.scope(0.0):
            sparse.linalg.gmres(A, b, restart=10, maxiter=1000)
    assert ei.value.site == "solver.gmres.conv"
    assert obs.counters.get("resil.deadline.solver") == 2


def test_injected_latency_expires_solver_deadline(resil):
    """The never-hangs acceptance drill: injected per-cycle latency
    pushes the solve past its budget; the result is a typed outcome
    with partial state, not a hang and not garbage."""
    A = _tridiag(512)
    b = np.ones(512, np.float32)
    resilience.inject("solver.gmres.conv", kind="latency",
                      latency_ms=40.0, count=100)
    with pytest.raises(resilience.DeadlineExceeded) as ei:
        with rdeadline.scope(30.0):
            sparse.linalg.gmres(A, b, restart=5, maxiter=10_000,
                                rtol=1e-12)
    assert ei.value.iterations >= 0
    assert ei.value.partial is not None
    resilience.faults.clear()


# ---------------------------------------------------------------------------
# health: structured outcomes instead of silent NaN
# ---------------------------------------------------------------------------
def test_health_nonfinite_surfaced_gmres(resil):
    settings.resil_health = True
    A = _tridiag(128)
    b = np.ones(128, np.float32)
    resilience.inject("solver.gmres.conv", kind="nonfinite", count=1)
    with pytest.raises(resilience.SolverHealthError) as ei:
        sparse.linalg.gmres(A, b, restart=10, maxiter=100)
    rep = ei.value.report
    assert rep.cause == "non_finite"
    assert rep.site == "solver.gmres.conv"
    assert rep.iterations > 0
    assert np.isnan(rep.residual)
    assert ei.value.partial is not None
    assert obs.counters.get(
        "resil.health.solver.gmres.conv.non_finite") == 1
    resilience.faults.clear()


def test_health_nonfinite_surfaced_cg(resil):
    settings.resil_health = True
    A = _tridiag(256)
    b = np.ones(256, np.float32)
    resilience.inject("solver.cg.conv", kind="nonfinite", count=1)
    with pytest.raises(resilience.SolverHealthError) as ei:
        sparse.linalg.cg(A, b, maxiter=100)
    assert ei.value.report.cause == "non_finite"
    assert ei.value.report.site == "solver.cg.conv"
    resilience.faults.clear()


def test_health_off_keeps_old_semantics(resil):
    """Without the health opt-in a poisoned residual does NOT raise —
    the solve keeps the pre-subsystem return semantics."""
    assert settings.resil_health is False
    A = _tridiag(128)
    b = np.ones(128, np.float32)
    resilience.inject("solver.gmres.conv", kind="nonfinite", count=1)
    x, it = sparse.linalg.gmres(A, b, restart=10, maxiter=50)
    assert int(it) >= 0
    resilience.faults.clear()


def test_health_stagnation_detected(resil):
    """GMRES(1) on a skew rotation classically stagnates (r ⟂ Ar):
    the stagnation monitor must call it instead of burning maxiter."""
    settings.resil_health = True
    settings.resil_stagnation_cycles = 3
    A = sparse.csr_array(np.array([[0.0, 1.0], [-1.0, 0.0]],
                                  dtype=np.float32))
    b = np.array([1.0, 0.0], np.float32)
    with pytest.raises(resilience.SolverHealthError) as ei:
        sparse.linalg.gmres(A, b, restart=1, maxiter=500)
    assert ei.value.report.cause == "stagnation"


# ---------------------------------------------------------------------------
# satellite: executor atexit drain regression (executor.py:207 daemon
# thread dropped queued requests at interpreter exit)
# ---------------------------------------------------------------------------
_ATEXIT_DRILL = r"""
import atexit, sys
import numpy as np
import jax.numpy as jnp
import scipy.sparse as sp
import legate_sparse_tpu as sparse
from legate_sparse_tpu.settings import settings
from legate_sparse_tpu.engine import Engine, RequestExecutor

S = sp.random(200, 200, density=0.05, random_state=0, format="csr",
              dtype=np.float32)
A = sparse.csr_array(S)
x = jnp.ones((200,), jnp.float32)
expected = np.asarray(A @ x)
holder = {}

def check():
    # Runs AFTER the executor's own atexit drain (atexit is LIFO and
    # this registers first): the queued request must have been
    # dispatched, not dropped.
    fut = holder.get("fut")
    ok = (fut is not None and fut.done()
          and fut.exception() is None
          and np.allclose(np.asarray(fut.result()), expected))
    sys.stdout.write("DISPATCHED=%d\n" % (1 if ok else 0))
    sys.stdout.flush()

atexit.register(check)
settings.engine = True
ex = RequestExecutor(Engine(), max_batch=8, queue_depth=64,
                     timeout_ms=60000.0)   # worker won't fire in time
holder["fut"] = ex.submit(A, x)
assert ex.pending() == 1
# exit WITHOUT flush/shutdown: only the atexit hook can drain.
"""


def test_executor_atexit_drains_queued_requests(tmp_path):
    script = tmp_path / "atexit_drill.py"
    script.write_text(_ATEXIT_DRILL)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)], cwd=REPO,
                       env=env, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DISPATCHED=1" in r.stdout, (r.stdout, r.stderr[-2000:])


# ---------------------------------------------------------------------------
# ledger rendering
# ---------------------------------------------------------------------------
def test_render_resil_table_from_live_counters(resil):
    A = _rand_csr(seed=13)
    x = jnp.ones((A.shape[1],), jnp.float32)
    resilience.inject("csr.dot", kind="error", count=2)
    _ = A @ x
    from legate_sparse_tpu.obs import report

    table = report.render_resil_table(obs.counters.snapshot())
    assert "csr.dot" in table
    assert "retries: 2 attempts" in table
    resilience.faults.clear()


# ---------------------------------------------------------------------------
# review regressions: probe-slot release on verdicts, nested-breaker
# ladder flip, no negative-cache poison, executor collectability
# ---------------------------------------------------------------------------
def test_probe_release_on_final_outcome_verdict(resil):
    """A half-open probe that ends in a resilience VERDICT (not a
    success or a failure) must release the probe slot — otherwise the
    breaker wedges in half_open forever (no time-based exit)."""
    from legate_sparse_tpu.resilience import outcomes, policy

    settings.resil_retries = 0
    settings.resil_breaker_k = 2
    settings.resil_breaker_cooldown_ms = 30.0
    site = "csr.dot"

    def boom():
        raise RuntimeError("transient")

    for _ in range(2):
        with pytest.raises(RuntimeError):
            policy.run(site, boom)
    assert policy.breaker(site).state == "open"
    time.sleep(0.05)                     # past cooldown

    def verdict():
        raise outcomes.DeadlineExceeded(site)

    with pytest.raises(outcomes.DeadlineExceeded):
        policy.run(site, verdict)        # elected probe, ends in verdict
    # Slot released: the NEXT call must be admitted as the probe and
    # heal the breaker instead of short-circuiting forever.
    assert policy.run(site, lambda: 42) == 42
    assert policy.breaker(site).state == "closed"


def test_open_plan_build_breaker_flips_ladder_no_poison(resil):
    """An open engine.plan.build breaker must not escape ``A @ x`` as
    CircuitOpenError ('engine on is always safe'): the route flips to
    the plain dispatch.  And the short-circuit must not poison the
    plan negative cache — the key builds normally once the breaker
    heals."""
    from legate_sparse_tpu.resilience import policy

    saved = settings.engine
    try:
        settings.engine = True
        settings.resil_retries = 0
        settings.resil_breaker_k = 1
        settings.resil_breaker_cooldown_ms = 60000.0   # stays open
        A = _rand_csr(n=520, seed=11)
        x = jnp.ones((520,), jnp.float32)
        # Delta, not absolute: earlier tests (test_engine's negative-
        # cache drills) legitimately advance the process-wide counter.
        ff0 = obs.counters.get("engine.plan.failed_fast")
        br = policy.breaker("engine.plan.build")
        br.record_failure()              # K=1: open before any build
        assert br.state == "open"
        y = np.asarray(A @ x)            # ladder flip, no raise
        settings.engine = False
        expect = np.asarray(A @ x)
        assert np.array_equal(y, expect)
        settings.engine = True
        policy.reset()                   # breaker heals
        y2 = np.asarray(A @ x)           # same key must build now
        # allclose, not array_equal: the engine's bucketed kernel may
        # differ from the plain dispatch's structure path in the last
        # float bits (documented ladder-flip caveat, RESILIENCE.md).
        assert np.allclose(y2, expect, rtol=1e-5, atol=1e-6)
        assert obs.counters.get("engine.plan.failed_fast") == ff0, \
            "short-circuited key leaked into the plan negative cache"
    finally:
        settings.engine = saved


def test_executor_abandoned_is_collectable():
    """An executor dropped without shutdown() must stay garbage-
    collectable (flush-only mode: no worker thread) — the exit drain
    tracks it weakly, never via a strong atexit bound-method ref that
    would pin its _anchors matrices for process lifetime."""
    import gc
    import weakref as _wr

    from legate_sparse_tpu.engine import Engine, RequestExecutor

    ex = RequestExecutor(Engine(), max_batch=4, queue_depth=8,
                         timeout_ms=0)
    ref = _wr.ref(ex)
    del ex
    gc.collect()
    assert ref() is None, "abandoned executor pinned by the exit drain"


def test_retry_loop_stops_on_self_tripped_breaker(resil):
    """A call whose own failures trip the breaker must stop retrying
    (the open breaker is consulted between attempts) — a tripped site
    does not keep getting hammered from inside one retry ladder."""
    settings.resil_retries = 5
    settings.resil_breaker_k = 2
    settings.resil_breaker_cooldown_ms = 60000.0   # stays open
    A = _rand_csr(seed=21)
    x = jnp.ones((A.shape[1],), jnp.float32)
    resilience.inject("csr.dot", kind="error", count=10)
    with pytest.raises(resilience.InjectedFault):
        A @ x
    # Exactly 2 attempts executed (K=2 tripped after the 2nd), not
    # 1 + retries: one retry granted, then the open breaker halts.
    assert resilience.faults.fired("csr.dot") == 2
    assert obs.counters.get("resil.retry.csr.dot") == 1
    assert resilience.breaker("csr.dot").state == "open"
    resilience.faults.clear()


def test_nonfinite_fault_on_spgemm_is_noop(resil):
    """A nonfinite fault armed on csr.dot must degrade to a no-op
    fire for the SpGEMM dispatch (csr_array result is not poisonable)
    instead of surfacing a TypeError the retry ladder would misread
    as a site failure."""
    A = _rand_csr(seed=22)
    clean = (A @ A).toarray()
    resilience.inject("csr.dot", kind="nonfinite", count=1)
    out = (A @ A).toarray()
    assert resilience.faults.fired("csr.dot") == 1
    assert obs.counters.get("resil.retry.csr.dot") == 0
    assert np.array_equal(out, clean)
    resilience.faults.clear()


# ---------------------------------------------------------------------------
# satellite: typed Rejected.reason vocabulary (closed, backward compat)
# ---------------------------------------------------------------------------
def test_rejected_reason_typed_vocabulary():
    from legate_sparse_tpu.resilience import outcomes

    assert outcomes.Rejected(site="s.x").reason == "deadline_shed"
    # Legacy spelling (pre-typed executor sheds) normalizes.
    assert outcomes.Rejected(site="s.x",
                             reason="deadline").reason == "deadline_shed"
    for reason in outcomes.REJECT_REASONS:
        assert outcomes.Rejected(site="s.x", reason=reason).reason == reason
    with pytest.raises(ValueError):
        outcomes.Rejected(site="s.x", reason="because")


def test_executor_shed_carries_typed_reason(resil):
    saved = settings.engine
    from legate_sparse_tpu.engine import Engine, RequestExecutor

    try:
        settings.engine = True
        A = _rand_csr(seed=23)
        x = jnp.ones((A.shape[1],), jnp.float32)
        ex = RequestExecutor(Engine(), max_batch=8, queue_depth=64,
                             timeout_ms=0)
        with rdeadline.scope(0.0):
            out = ex.submit(A, x).result(timeout=10)
        assert isinstance(out, resilience.Rejected)
        assert out.reason == "deadline_shed"
        ex.shutdown()
    finally:
        settings.engine = saved


# ---------------------------------------------------------------------------
# satellite: monotonic-clock internals (breaker cooldown, deadlines)
# ---------------------------------------------------------------------------
def test_breaker_cooldown_on_frozen_monotonic_clock(resil, monkeypatch):
    """Breaker cooldown arithmetic runs on ``time.monotonic_ns()``
    read at call time: under a frozen clock an open breaker never
    half-opens, and advancing the fake clock past the cooldown
    admits exactly the probe — no wall-clock sleeps, no flakiness."""
    from legate_sparse_tpu.resilience import policy

    now = {"ns": 1_000_000_000}
    monkeypatch.setattr(time, "monotonic_ns", lambda: now["ns"])
    br = policy.CircuitBreaker("drill.site", k=1, cooldown_s=0.05)
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()                      # frozen: still cooling
    now["ns"] += 49_000_000
    assert not br.allow()                      # 49 ms < 50 ms cooldown
    now["ns"] += 2_000_000
    assert br.allow()                          # past cooldown: probe
    assert br.state == "half_open"
    br.record_success()
    assert br.state == "closed"


def test_deadline_tracks_patched_monotonic_clock(resil, monkeypatch):
    from legate_sparse_tpu.resilience import deadline as dl

    now = {"ns": 5_000_000_000}
    monkeypatch.setattr(time, "monotonic_ns", lambda: now["ns"])
    with dl.scope(100.0):
        d = dl.current()
        assert d is not None
        assert abs(d.remaining_ms() - 100.0) < 1e-9
        assert not d.expired()
        now["ns"] += 60_000_000
        assert abs(d.remaining_ms() - 40.0) < 1e-9
        now["ns"] += 40_000_000
        assert d.expired()
        assert d.remaining_ms() <= 0.0
        # Sooner-wins nesting compares the integer end instants.
        with dl.scope(10_000.0):
            assert dl.current().t_end_ns == d.t_end_ns


# ---------------------------------------------------------------------------
# satellite: shutdown race — concurrent submit() vs close(), every
# accepted Future resolves exactly once (or the submit raises)
# ---------------------------------------------------------------------------
def test_executor_shutdown_race_resolves_every_future(resil):
    import threading

    saved = settings.engine
    from legate_sparse_tpu.engine import Engine, RequestExecutor

    try:
        settings.engine = True
        A = _rand_csr(seed=24)
        x = jnp.ones((A.shape[1],), jnp.float32)
        expected = np.asarray(A @ x)
        for trial in range(3):
            ex = RequestExecutor(Engine(), max_batch=64,
                                 queue_depth=256, timeout_ms=60000.0)
            futs, raised = [], []
            barrier = threading.Barrier(5)

            def _submitter():
                barrier.wait()
                for _i in range(8):
                    try:
                        futs.append(ex.submit(A, x))
                    except RuntimeError:
                        # Landed after shutdown: allowed, as long as
                        # nothing was enqueued (no orphaned Future).
                        raised.append(1)

            def _closer():
                barrier.wait()
                ex.close()

            threads = ([threading.Thread(target=_submitter)
                        for _t in range(4)]
                       + [threading.Thread(target=_closer)])
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            ex.close()       # idempotent final drain
            assert len(futs) + len(raised) == 32
            for f in futs:
                out = f.result(timeout=30)   # never hangs
                if isinstance(out, resilience.Rejected):
                    continue
                assert np.array_equal(np.asarray(out), expected)
    finally:
        settings.engine = saved


def test_gateway_shutdown_race_resolves_every_future(resil):
    import threading

    saved = settings.gateway
    from legate_sparse_tpu.engine import Engine, Gateway

    try:
        settings.gateway = True
        A = _rand_csr(seed=25)
        x = jnp.ones((A.shape[1],), jnp.float32)
        expected = np.asarray(A @ x)
        for trial in range(3):
            gw = Gateway(Engine(), max_batch=64, queue_depth=256,
                         tenant_quota=64, rate=0.0, burst=16.0,
                         slack_ms=5.0, timeout_ms=60000.0)
            futs, raised = [], []
            barrier = threading.Barrier(5)

            def _submitter(name):
                barrier.wait()
                for _i in range(8):
                    try:
                        futs.append(gw.submit(A, x, tenant=name,
                                              qos="batch"))
                    except RuntimeError:
                        raised.append(1)

            def _closer():
                barrier.wait()
                gw.close()

            threads = ([threading.Thread(target=_submitter,
                                         args=(f"t{i}",))
                        for i in range(4)]
                       + [threading.Thread(target=_closer)])
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            gw.close()
            assert len(futs) + len(raised) == 32
            for f in futs:
                out = f.result(timeout=30)   # never hangs
                if isinstance(out, resilience.Rejected):
                    continue
                assert np.array_equal(np.asarray(out), expected)
    finally:
        settings.gateway = saved
