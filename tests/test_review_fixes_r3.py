# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Round-3 advisor-finding regressions (ADVICE.md r2)."""

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg


def test_random_default_format_is_coo():
    from legate_sparse_tpu.coo import coo_array

    A = sparse.random(50, 40, density=0.1, random_state=0)
    assert isinstance(A, coo_array)
    assert sparse.random(50, 40, density=0.1, format="csr",
                         random_state=0).format == "csr"


def test_setdiag_empty_values_noop():
    # scipy 1.17 silently no-ops on a zero-length values array.
    A = sparse.eye(4, format="csr")
    before = A.toarray().copy()
    A.setdiag(np.array([]))
    np.testing.assert_array_equal(A.toarray(), before)


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("build", ["sparse", "dense3x2", "dense2x3"])
def test_norm_axis_neg_inf_and_zero(axis, build):
    rng = np.random.default_rng(0)
    if build == "sparse":
        A_sp = sp.random(9, 7, density=0.4, format="csr",
                         random_state=rng)
        if A_sp.nnz:
            A_sp.data[0] = 0.0  # explicit zero: ord=0 must not count it
    elif build == "dense3x2":
        # Fully stored non-square: no implicit zeros anywhere, so
        # ord=-inf must NOT collapse to 0 (dimension-mix regression).
        A_sp = sp.csr_matrix(np.array([[1., 2.], [3., 4.], [5., 6.]]))
    else:
        A_sp = sp.csr_matrix(np.array([[1., 2., 7.], [3., 4., 8.]]))
    A = sparse.csr_array(A_sp)
    for order in (-np.inf, 0, 1, np.inf, None):
        got = linalg.norm(A, ord=order, axis=axis)
        want = sp.linalg.norm(A_sp, ord=order, axis=axis)
        assert isinstance(got, np.ndarray)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-12)


@pytest.mark.parametrize("fn", ["vstack", "hstack", "block_diag"])
def test_stack_empty_blocks_raise(fn):
    with pytest.raises(ValueError, match="empty"):
        getattr(sparse, fn)([])


@pytest.mark.parametrize("nq", [40, 200])  # small-loop and batched paths
def test_pointwise_get_vectorized_matches_scipy(nq):
    rng = np.random.default_rng(3)
    A_sp = sp.random(64, 48, density=0.15, format="csr", random_state=rng)
    A = sparse.csr_array(A_sp)
    rows = rng.integers(-64, 64, size=nq)
    cols = rng.integers(-48, 48, size=nq)
    got = A._pointwise_get(rows.copy(), cols.copy())
    want = np.array([A_sp[int(i) % 64, int(j) % 48]
                     for i, j in zip(rows, cols)])
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_pointwise_get_duplicates_summed():
    r = np.array([1, 1, 2])
    c = np.array([3, 3, 0])
    v = np.array([2.0, 5.0, 1.0])
    A = sparse.csr_array((v, (r, c)), shape=(4, 5))
    got = A._pointwise_get(np.array([1, 2, 0]), np.array([3, 0, 0]))
    np.testing.assert_allclose(got, [7.0, 1.0, 0.0])


# ---- round-3 mid-round review findings ----

@pytest.mark.parametrize("fmt", ["csr", "coo", "csc", "dia"])
def test_spmatrix_rmul_is_vec_matmul(fmt):
    # x * M for *_matrix flavors is x @ M (scipy spmatrix semantics);
    # coo/csc used to shadow the mixin and silently compute M @ x.
    rng = np.random.default_rng(0)
    D = rng.standard_normal((5, 7)).astype(np.float32)
    D[D < 0.3] = 0
    x = rng.standard_normal(5).astype(np.float32)
    S = getattr(sp, fmt + "_matrix")(D)
    M = getattr(sparse, fmt + "_matrix")(sparse.csr_array(D).asformat(fmt))
    np.testing.assert_allclose(np.asarray(x * M).ravel(),
                               np.asarray(x * S).ravel(), rtol=1e-5)


@pytest.mark.parametrize("fmt", ["csr", "coo", "csc", "dia"])
def test_sum_list_and_rsub_zero(fmt):
    # sum([A, B]) hits 0 + A -> __radd__(0); 0 - A must negate.
    rng = np.random.default_rng(1)
    D = rng.standard_normal((6, 4)).astype(np.float32)
    D[D < 0.2] = 0
    A = sparse.csr_array(D).asformat(fmt)
    np.testing.assert_allclose(sum([A, A]).toarray(), 2 * D, rtol=1e-5)
    np.testing.assert_allclose((0 - A).toarray(), -D, rtol=1e-6)
    with pytest.raises(NotImplementedError):
        _ = np.ones_like(D) - A


def test_multiply_broadcast_row_col_vectors():
    # scipy multiply broadcasts (1, n) and (m, 1) without densifying.
    rng = np.random.default_rng(2)
    D = rng.standard_normal((5, 7)).astype(np.float32)
    D[D < 0.3] = 0
    row = rng.standard_normal(7).astype(np.float32)
    col = rng.standard_normal(5).astype(np.float32)
    A = sparse.csr_array(D)
    S = sp.csr_array(D)
    np.testing.assert_allclose(A.multiply(row[None, :]).toarray(),
                               S.multiply(row[None, :]).toarray(), rtol=1e-5)
    np.testing.assert_allclose(A.multiply(col[:, None]).toarray(),
                               S.multiply(col[:, None]).toarray(), rtol=1e-5)
