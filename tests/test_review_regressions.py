# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Regression tests for issues found in code review."""

import numpy as np
import pytest
import scipy.sparse as scsp

import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg
from utils_test.gen import random_csr, spd_system


def test_multiply_with_duplicate_entries():
    # COO input with duplicates: elementwise product must match scipy
    # (square of summed values, not sum of squared values).
    L = sparse.csr_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 2),
    )
    S = scsp.csr_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 2),
    )
    result = L.multiply(L)
    expected = S.multiply(S).todense()
    np.testing.assert_allclose(np.asarray(result.todense()), expected)


def test_multiply_differing_patterns():
    sa = random_csr(9, 7, 0.4, 1)
    sb = random_csr(9, 7, 0.4, 2)
    A = sparse.csr_array(sa)
    B = sparse.csr_array(sb)
    np.testing.assert_allclose(
        np.asarray(A.multiply(B).todense()),
        np.asarray(sa.multiply(sb).todense()),
        atol=1e-14,
    )


def test_multiply_scipy_operand():
    sa = random_csr(6, 6, 0.5, 3)
    A = sparse.csr_array(sa)
    np.testing.assert_allclose(
        np.asarray(A.multiply(sa).todense()),
        np.asarray(sa.multiply(sa).todense()),
        atol=1e-14,
    )


def test_cg_x0_dtype_mismatch():
    N = 64
    A_dense, x = spd_system(N, 0.2, 5)
    A = sparse.csr_array(A_dense)
    y = A @ x
    # float32 x0 against float64 b must cast, not crash the while_loop.
    x_pred, _ = linalg.cg(A, y, x0=np.zeros(N, dtype=np.float32), tol=1e-8)
    np.testing.assert_allclose(
        np.asarray(A @ x_pred), np.asarray(y), rtol=1e-8, atol=1e-10
    )


def test_has_canonical_format_tracking():
    # COO with duplicates: not canonical until sum_duplicates.
    L = sparse.csr_array(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(2, 2),
    )
    assert not L.has_canonical_format
    L.sum_duplicates()
    assert L.has_canonical_format
    assert L.nnz == 1
    np.testing.assert_allclose(np.asarray(L.data), [3.0])
    # Dense constructor output is canonical.
    A = sparse.csr_array(np.eye(3))
    assert A.has_canonical_format


def test_dia_spmv_fast_path():
    d = sparse.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(16, 16))
    s = scsp.diags([-1.0, 2.0, -1.0], [-1, 0, 1], shape=(16, 16))
    x = np.random.default_rng(0).standard_normal(16)
    np.testing.assert_allclose(np.asarray(d @ x), s @ x, atol=1e-13)
    X = np.random.default_rng(1).standard_normal((16, 4))
    np.testing.assert_allclose(np.asarray(d @ X), s @ X, atol=1e-13)


def test_dia_rectangular_spmv():
    d = sparse.diags([[1, 2, 3, 4], [4, 5, 6]], [0, 1], shape=(5, 4))
    s = scsp.diags([[1, 2, 3, 4], [4, 5, 6]], [0, 1], shape=(5, 4),
                   dtype=np.float64)
    x = np.arange(4.0)
    np.testing.assert_allclose(np.asarray(d.astype(np.float64) @ x), s @ x)


def test_ell_padding_does_not_poison_rows():
    """Padded ELL slots contribute an exact 0 even against non-finite x:
    rows not touching the inf column stay finite, a row touching it
    yields inf (not nan), and an empty row yields exactly 0."""
    # Row 0 has 2 nnz (both col>=1), row 1 has 1 nnz -> W=2, one pad slot.
    A = sparse.csr_array(
        (np.array([1.0, 2.0, 3.0]), np.array([1, 2, 2]),
         np.array([0, 2, 3])),
        shape=(2, 3),
    )
    x = np.array([np.inf, 1.0, 1.0])
    np.testing.assert_allclose(np.asarray(A @ x), [3.0, 3.0])
    # Row 0 touches the inf column with 1 valid + 1 padded slot:
    # 1*inf + pad must be inf, not nan (pad product masked, 0*inf trap).
    B = sparse.csr_array(
        (np.array([1.0, 2.0, 3.0]), np.array([0, 0, 1]),
         np.array([0, 1, 3])),
        shape=(2, 2),
    )
    yB = np.asarray(B @ np.array([np.inf, 1.0]))
    assert np.isinf(yB[0]) and not np.isnan(yB[0])
    # Empty middle row stays exactly 0 against inf anywhere in x.
    C = sparse.csr_array(
        (np.array([1.0, 2.0]), np.array([0, 1]), np.array([0, 1, 1, 2])),
        shape=(3, 2),
    )
    yC = np.asarray(C @ np.array([np.inf, 1.0]))
    assert yC[1] == 0.0


def test_matvec_traceable_in_data():
    """A @ x must stay jit-traceable w.r.t. the matrix data."""
    import jax
    import jax.numpy as jnp

    idx = np.array([0, 1, 1], dtype=np.int32)
    ptr = np.array([0, 2, 3], dtype=np.int64)
    x = jnp.asarray(np.array([1.0, 2.0]))

    @jax.jit
    def f(d):
        A = sparse.csr_array((d, idx, ptr), shape=(2, 2))
        return A @ x

    y = np.asarray(f(jnp.asarray(np.array([1.0, 2.0, 3.0]))))
    np.testing.assert_allclose(y, [5.0, 6.0])


def test_data_update_reuses_structure():
    """Updating .data keeps the cached ELL width and stays correct."""
    A = sparse.csr_array(np.array([[1.0, 0.0], [0.0, 2.0]]))
    x = np.array([1.0, 1.0])
    np.testing.assert_allclose(np.asarray(A @ x), [1.0, 2.0])
    A.data = np.array([3.0, 4.0])
    np.testing.assert_allclose(np.asarray(A @ x), [3.0, 4.0])


def test_dist_padded_csr_fallback_masks_padding():
    """Padded-CSR distributed fallback: padding slots must contribute an
    exact 0 even when x holds non-finite values (reviewer repro)."""
    import jax
    from legate_sparse_tpu.parallel import shard_csr, dist_spmv
    from legate_sparse_tpu.parallel.dist_csr import shard_vector
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    dense = np.array(
        [[1.0, 1.0, 0.0, 0.0],
         [0.0, 2.0, 0.0, 0.0],
         [0.0, 1.0, 3.0, 0.0],
         [0.0, 0.0, 0.0, 0.0]]
    )
    A = sparse.csr_array(dense)
    mesh = make_row_mesh(jax.devices()[:2])
    dA = shard_csr(A, mesh=mesh, ell_max_expand=0)  # force CSR fallback
    assert not dA.ell
    x = shard_vector(np.array([1.0, np.inf, 1.0, 1.0]), mesh,
                     dA.rows_padded)
    y = np.asarray(dist_spmv(dA, x))[:4]
    assert np.isinf(y[0]) and np.isinf(y[1]) and np.isinf(y[2])
    assert y[3] == 0.0
