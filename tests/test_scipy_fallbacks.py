# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Adapted scipy fallbacks: names without a native implementation work
with this package's arrays (converted at the boundary) instead of
being coerced to object arrays by raw scipy functions."""

import numpy as np
import pytest
import scipy.sparse as sp

import legate_sparse_tpu as lst
import legate_sparse_tpu.linalg as linalg


@pytest.fixture
def pair():
    A = lst.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(16, 16),
                  format="csr")
    As = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(16, 16)).tocsr()
    return A, As


def test_linalg_spsolve(pair):
    A, As = pair
    b = np.ones(16)
    x = linalg.spsolve(A, b)
    assert np.linalg.norm(As @ x - b) < 1e-10


def test_linalg_eigsh(pair):
    A, As = pair
    w = linalg.eigsh(A, k=3, return_eigenvectors=False)
    ws = sp.linalg.eigsh(As, k=3, return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(ws), rtol=1e-9)


def test_linalg_expm_returns_native(pair):
    A, As = pair
    e = linalg.expm(A.tocsc())
    # Result converts back into this package's array types.
    assert type(e).__module__.startswith("legate_sparse_tpu")
    np.testing.assert_allclose(
        np.asarray(e.toarray()),
        sp.linalg.expm(As.tocsc()).toarray(), rtol=1e-9,
    )


def test_linalg_unknown_name_raises():
    with pytest.raises(AttributeError):
        linalg.definitely_not_a_solver  # noqa: B018


def test_toplevel_fallback_accepts_native_arrays(pair):
    """A cloned scipy function we have no native version of converts
    arguments and results at the boundary."""
    A, As = pair
    # random_array has no native override: its scipy result must come
    # back as this package's array type (the _from_scipy path).
    assert getattr(lst.random_array, "_lst_scipy_fallback", False)
    R = lst.random_array((8, 6), density=0.5, random_state=np.random.default_rng(0))
    assert type(R).__module__.startswith("legate_sparse_tpu")
    assert R.shape == (8, 6)
    # kron with a scipy operand mixes both worlds through the facade.
    K = lst.kron(A, As)
    np.testing.assert_allclose(
        np.asarray(K.toarray()), sp.kron(As, As).toarray()
    )


def test_fallback_identity_cached():
    import legate_sparse_tpu.linalg as L

    assert L.spsolve is L.spsolve


def test_dia_array_through_fallback(pair):
    """dia_array converts at the boundary too (it has toscipy now)."""
    A, _ = pair
    D = A.todia()
    b = np.ones(16)
    x = linalg.spsolve(D.tocsr().tocsc(), b)
    x2 = linalg.spsolve(D, b)
    np.testing.assert_allclose(x, x2)
