# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""The two reference settings flags must change observable behavior
(VERDICT r1 item 6): precise images -> all_to_all exact gathers;
fast_spgemm off + small chunk -> chunked low-memory ESC."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu.ops import spgemm as spgemm_mod
from legate_sparse_tpu.parallel import make_row_mesh, shard_csr, dist_spmv
from legate_sparse_tpu.parallel.dist_csr import shard_vector
from legate_sparse_tpu.settings import settings

needs_multi = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple devices"
)


def _adversarial_csr(n):
    """Banded matrix plus one long-range row: the min/max window
    realization degenerates to (nearly) all_gather, a precise image
    stays narrow."""
    A = sp.diags([1.0, -2.0, 1.0], [-1, 0, 1], shape=(n, n)).tolil()
    A[1, n - 1] = 7.0             # one long-range entry
    return A.tocsr()


@needs_multi
def test_precise_images_flag_changes_layout_and_matches():
    n = 128
    A_sp = _adversarial_csr(n)
    A = sparse.csr_array(A_sp)
    mesh = make_row_mesh()
    R = len(mesh.devices)

    # force_all_gather pins the full-realization baseline (an explicit
    # precise=False would still auto-upgrade: the long-range row blows
    # the halo window, and the blown-halo fallback prefers precise).
    d_window = shard_csr(A, mesh=mesh, precise=False,
                         force_all_gather=True)
    d_precise = shard_csr(A, mesh=mesh, precise=True)
    assert d_window.gather_idx is None
    assert d_precise.gather_idx is not None
    # Precise plan ships O(unique cols) per shard, far below a full
    # x realization.
    C = d_precise.gather_idx.shape[-1]
    assert R * C < n

    x = np.linspace(-1.0, 1.0, n)
    xs = shard_vector(x, mesh, d_precise.rows_padded)
    y_p = np.asarray(dist_spmv(d_precise, xs))[:n]
    y_w = np.asarray(dist_spmv(d_window, xs))[:n]
    y_ref = A_sp @ x
    np.testing.assert_allclose(y_p, y_ref, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(y_w, y_ref, rtol=1e-12, atol=1e-12)


@needs_multi
def test_precise_images_env_default(monkeypatch):
    monkeypatch.setattr(settings, "precise_images", True)
    A = sparse.diags([1.0, 2.0], [-1, 0], shape=(32, 32), format="csr")
    dA = shard_csr(A, mesh=make_row_mesh())
    assert dA.gather_idx is not None
    np.testing.assert_allclose(
        dA.to_csr().toscipy().toarray(), A.toscipy().toarray()
    )


@needs_multi
def test_precise_images_through_spgemm_and_diagonal():
    from legate_sparse_tpu.parallel import dist_diagonal, dist_spgemm

    n = 64
    A_sp = _adversarial_csr(n)
    mesh = make_row_mesh()
    dA = shard_csr(sparse.csr_array(A_sp), mesh=mesh, precise=True)
    np.testing.assert_allclose(
        np.asarray(dist_diagonal(dA))[:n], A_sp.diagonal()
    )
    dC = dist_spgemm(dA, dA)
    np.testing.assert_allclose(
        dC.to_csr().toscipy().toarray(), (A_sp @ A_sp).toarray(),
        rtol=1e-12, atol=1e-12,
    )


@pytest.mark.slow
def test_chunked_spgemm_matches_single_shot(monkeypatch):
    rng = np.random.RandomState(11)
    A_sp = sp.random(60, 48, density=0.15, random_state=rng,
                     format="csr", dtype=np.float64)
    B_sp = sp.random(48, 52, density=0.15, random_state=rng,
                     format="csr", dtype=np.float64)
    C_ref = (A_sp @ B_sp).toarray()

    A = sparse.csr_array(A_sp)
    B = sparse.csr_array(B_sp)

    monkeypatch.setattr(settings, "fast_spgemm", True)
    C_fast = (A @ B).toscipy().toarray()
    assert spgemm_mod._last_num_chunks == 1

    monkeypatch.setattr(settings, "fast_spgemm", False)
    monkeypatch.setattr(settings, "spgemm_chunk_products", 97)
    C_chunked = (A @ B).toscipy().toarray()
    assert spgemm_mod._last_num_chunks > 1

    np.testing.assert_allclose(C_fast, C_ref, rtol=1e-12, atol=1e-14)
    np.testing.assert_allclose(C_chunked, C_ref, rtol=1e-12, atol=1e-14)


def test_check_bounds_mode(monkeypatch):
    """Debug bounds checking (reference --check-bounds analog) rejects
    out-of-range indices and inconsistent indptr at construction."""
    monkeypatch.setattr(settings, "check_bounds", True)
    # Valid matrix passes.
    sparse.csr_array(
        (np.ones(2), np.array([0, 1]), np.array([0, 1, 2])), shape=(2, 2)
    )
    with pytest.raises(IndexError, match="column indices out of range"):
        sparse.csr_array(
            (np.ones(2), np.array([0, 5]), np.array([0, 1, 2])),
            shape=(2, 2),
        )
    with pytest.raises(IndexError, match="indptr"):
        sparse.csr_array(
            (np.ones(2), np.array([0, 1]), np.array([0, 3, 2])),
            shape=(2, 2),
        )


def test_chunked_spgemm_single_heavy_row(monkeypatch):
    # One A-nonzero whose B row alone exceeds the chunk budget must
    # still be processed (its own chunk).
    n = 40
    A_sp = sp.csr_matrix(
        (np.ones(2), (np.array([0, 1]), np.array([0, 1]))), shape=(n, n)
    )
    B_dense = np.zeros((n, n))
    B_dense[0, :] = 1.0           # B row 0 has n products
    B_dense[1, :3] = 2.0
    B_sp = sp.csr_matrix(B_dense)
    monkeypatch.setattr(settings, "fast_spgemm", False)
    monkeypatch.setattr(settings, "spgemm_chunk_products", 5)
    C = (sparse.csr_array(A_sp) @ sparse.csr_array(B_sp)).toscipy()
    np.testing.assert_allclose(C.toarray(), (A_sp @ B_sp).toarray())
    assert spgemm_mod._last_num_chunks >= 2
