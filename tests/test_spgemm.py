# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SpGEMM differential tests (mirrors reference ``test_spgemm.py``)."""

import numpy as np
import pytest

import legate_sparse_tpu as sparse
from utils_test.gen import banded_matrix, random_csr, simple_system_gen


@pytest.mark.parametrize("N", [5, 29])
@pytest.mark.parametrize("M", [7, 17])
@pytest.mark.parametrize("K", [4, 21])
def test_spgemm_random(N, M, K):
    sa = random_csr(N, M, 0.4, 1)
    sb = random_csr(M, K, 0.4, 2)
    A = sparse.csr_array(sa)
    B = sparse.csr_array(sb)
    C = A @ B
    assert isinstance(C, sparse.csr_array)
    np.testing.assert_allclose(
        np.asarray(C.todense()), (sa @ sb).todense(), atol=1e-13
    )


@pytest.mark.parametrize("N", [16, 61])
def test_spgemm_banded(N):
    sa = banded_matrix(N, 5)
    A = sparse.csr_array(sa)
    C = A @ A
    np.testing.assert_allclose(
        np.asarray(C.todense()), (sa @ sa).todense(), atol=1e-12
    )
    # Structure parity: nnz after duplicate compression equals scipy's.
    assert C.nnz == (sa @ sa).nnz


def test_spgemm_dense_then_compare():
    a_dense, A, _ = simple_system_gen(12, 9, sparse.csr_array)
    b_dense, B, _ = simple_system_gen(9, 15, sparse.csr_array, seed=5)
    C = A @ B
    np.testing.assert_allclose(
        np.asarray(C.todense()), a_dense @ b_dense, atol=1e-13
    )


def test_spgemm_empty():
    A = sparse.csr_array(np.zeros((4, 6)))
    B = sparse.csr_array(np.zeros((6, 3)))
    C = A @ B
    assert C.nnz == 0
    assert C.shape == (4, 3)


def test_galerkin_triple_product():
    # The GMG use case (reference ``gmg.py:90-102``): A_c = R @ A @ P.
    N = 32
    A = sparse.csr_array(banded_matrix(N, 3))
    # Injection restriction: pick every other row.
    import scipy.sparse as scsp

    R_sp = scsp.csr_array(
        (np.ones(N // 2), (np.arange(N // 2), 2 * np.arange(N // 2))),
        shape=(N // 2, N),
    )
    R = sparse.csr_array(R_sp)
    P = R.T
    Ac = R @ A @ P
    expected = (R_sp @ banded_matrix(N, 3) @ R_sp.T).todense()
    np.testing.assert_allclose(np.asarray(Ac.todense()), expected)
