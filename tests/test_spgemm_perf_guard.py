# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Slow-lane SpGEMM perf guard (VERDICT r5 Weak #2).

The round-4 banded-SpGEMM win (``spgemm_vs_scipy`` ~1.5 in
``BENCH_r04``/``r05``) had no regression tripwire: a refactor could
silently demote the banded product back to the generic ESC path and
nothing would fail.  This guard re-runs the exact bench config —
n=65536 banded A·A, nnz/row=11 — against host scipy ON THE SAME BOX
(the same-box referee is what makes the ratio load-independent) and
asserts the package stays >= 1.2x scipy.

Slow lane on purpose: wall-time assertions do not belong in the
default tier-1 lane (``-m 'not slow'``); run with ``pytest -m slow``.
"""

import time

import numpy as np
import pytest

import legate_sparse_tpu as sparse


def _banded(n, nnz_per_row=11):
    half = nnz_per_row // 2
    offsets = list(range(-half, half + 1))
    val = np.float32(1.0 / nnz_per_row)
    diags = [np.full(n - abs(o), val, dtype=np.float32) for o in offsets]
    return sparse.diags(diags, offsets, shape=(n, n), format="csr",
                        dtype=np.float32)


def _best_of(fn, reps=5):
    best = float("inf")
    for rep in range(reps):
        t0 = time.perf_counter()
        fn()
        if rep:                      # rep 0 is warmup/compile
            best = min(best, time.perf_counter() - t0)
    return best


@pytest.mark.slow
def test_spgemm_banded_beats_scipy_by_1p2x():
    import scipy.sparse as sp

    n = 65536
    A = _banded(n)

    def ours():
        C = A @ A
        _ = float(np.asarray(C.data[0]))     # true completion sync

    A_host = sp.csr_matrix(
        (np.asarray(A.data), np.asarray(A.indices),
         np.asarray(A.indptr)), shape=A.shape)

    def scipy_ref():
        _ = A_host @ A_host

    best = _best_of(ours)
    best_sp = _best_of(scipy_ref)
    ratio = best_sp / max(best, 1e-9)
    assert ratio >= 1.2, (
        f"banded SpGEMM regressed: {best * 1e3:.2f} ms vs scipy "
        f"{best_sp * 1e3:.2f} ms on this box (ratio {ratio:.3f} < 1.2; "
        f"r04/r05 recorded ~1.5) — check the dia-pallas/dia-xla "
        f"dispatch before blaming machine noise")


@pytest.mark.slow
def test_spgemm_banded_result_matches_scipy():
    """Correctness referee for the guard config: the perf path must be
    producing the same product it is being timed on."""
    import scipy.sparse as sp

    n = 4096
    A = _banded(n)
    C = A @ A
    A_host = sp.csr_matrix(
        (np.asarray(A.data), np.asarray(A.indices),
         np.asarray(A.indptr)), shape=A.shape)
    C_host = (A_host @ A_host).tocsr()
    C_host.sort_indices()
    np.testing.assert_array_equal(np.asarray(C.indptr), C_host.indptr)
    np.testing.assert_array_equal(np.asarray(C.indices), C_host.indices)
    np.testing.assert_allclose(np.asarray(C.data), C_host.data,
                               rtol=1e-5, atol=1e-6)
