# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SpMV differential tests (mirrors reference ``test_spmv.py``)."""

import numpy as np
import pytest

import legate_sparse_tpu as sparse
from utils_test.gen import banded_matrix, simple_system_gen


@pytest.mark.parametrize("N", [5, 29])
@pytest.mark.parametrize("M", [7, 17])
@pytest.mark.parametrize("inline", [True, False])
def test_csr_spmv(N, M, inline):
    a_dense, A, x = simple_system_gen(N, M, sparse.csr_array)
    if inline:
        y = np.zeros((N,))
        A.dot(x, out=y)
    else:
        y = A @ x
    np.testing.assert_allclose(np.asarray(y), a_dense @ x, atol=1e-13)


@pytest.mark.parametrize("N", [5, 29])
@pytest.mark.parametrize("nnz_per_row", [3, 9])
@pytest.mark.parametrize("unsupported_dtype", ["int64", "bool"])
def test_csr_spmv_unsupported_dtype(N, nnz_per_row, unsupported_dtype):
    A = sparse.csr_array(banded_matrix(N, nnz_per_row)).astype(
        unsupported_dtype
    )
    x = np.zeros((N,))
    with pytest.raises(NotImplementedError):
        A.dot(x)


def test_csr_spmv_matrix_vector_column():
    a_dense, A, x = simple_system_gen(12, 12, sparse.csr_array)
    y = A @ x.reshape(-1, 1)
    assert y.shape == (12, 1)
    np.testing.assert_allclose(np.asarray(y).ravel(), a_dense @ x, atol=1e-13)


def test_csr_spmm_dense():
    a_dense, A, _ = simple_system_gen(10, 14, sparse.csr_array)
    X = np.random.default_rng(5).random((14, 6))
    Y = A @ X
    np.testing.assert_allclose(np.asarray(Y), a_dense @ X, atol=1e-13)


def test_spmv_free_function():
    a_dense, A, x = simple_system_gen(9, 9, sparse.csr_array)
    y = np.zeros(9)
    sparse.spmv(A, x, y)
    np.testing.assert_allclose(y, a_dense @ x, atol=1e-13)


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex64,
                                   np.complex128])
def test_spmv_dtypes(dtype):
    a_dense, A, x = simple_system_gen(8, 8, sparse.csr_array)
    A = A.astype(dtype)
    y = A @ x.astype(dtype)
    np.testing.assert_allclose(
        np.asarray(y), a_dense.astype(dtype) @ x.astype(dtype),
        rtol=1e-5 if dtype in (np.float32, np.complex64) else 1e-12,
    )
