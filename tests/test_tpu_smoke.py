# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""TPU smoke lane: the core kernels compile and match scipy on a real chip.

The rest of the suite pins the cpu platform for determinism
(``conftest.py``); this file is the continuously-runnable evidence that
SpMV / SpGEMM / CG compile and run on the accelerator — the role of the
reference's on-hardware ``legate --gpus 1`` test invocation.

Invocation (documented driver contract)::

    LEGATE_SPARSE_TPU_TEST_PLATFORM=tpu python -m pytest -m tpu tests/ -q

Under the default (cpu-pinned) suite these tests skip.
"""

import numpy as np
import pytest

import jax

import legate_sparse_tpu as sparse
from legate_sparse_tpu import linalg

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def accel():
    """Skip unless the default platform is an accelerator."""
    platform = jax.devices()[0].platform
    if platform == "cpu":
        pytest.skip(
            "no accelerator platform (set LEGATE_SPARSE_TPU_TEST_PLATFORM"
            "=tpu to run the smoke lane on a real chip)"
        )
    return platform


def _poisson(n_grid, dtype=np.float32):
    n = n_grid * n_grid
    return sparse.diags(
        [-1.0, -1.0, 4.0, -1.0, -1.0],
        [-n_grid, -1, 0, 1, n_grid],
        shape=(n, n), format="csr", dtype=dtype,
    )


def test_spmv_matches_scipy(accel):
    A = _poisson(16)
    x = np.linspace(-1.0, 1.0, A.shape[0]).astype(np.float32)
    y = np.asarray(A @ x)
    y_ref = A.toscipy() @ x
    np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-5)


def test_spgemm_matches_scipy(accel):
    A = _poisson(8)
    C = A @ A
    C_ref = (A.toscipy() @ A.toscipy()).tocsr()
    C_sp = C.toscipy()
    np.testing.assert_allclose(C_sp.toarray(), C_ref.toarray(),
                               rtol=1e-5, atol=1e-5)


def test_pallas_dia_kernel_on_chip(accel):
    """The Mosaic DIA kernel lowers, runs, and matches scipy on the
    real chip (not interpret mode)."""
    from legate_sparse_tpu.ops import pallas_dia

    A = _poisson(32)
    dia = A._get_dia()
    assert dia is not None
    dia_data, offsets, mask = dia
    packed = pallas_dia.pack_band(dia_data, offsets, A.shape, mask=mask)
    assert packed is not None
    x = np.linspace(-1.0, 1.0, A.shape[0]).astype(np.float32)
    y = np.asarray(pallas_dia.pallas_dia_spmv(
        packed.rdata, packed.rmask, x, packed.offsets, packed.shape,
        packed.tile, interpret=False,
    ))
    y_ref = A.toscipy() @ x
    np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-5)


def test_pallas_dia_spmm_on_chip(accel):
    from legate_sparse_tpu.ops import pallas_dia

    A = _poisson(32)
    packed = A._get_dia_pack()
    assert packed is not None
    X = np.linspace(-1.0, 1.0, A.shape[0] * 4).reshape(
        A.shape[0], 4).astype(np.float32)
    tile = pallas_dia._spmm_tile(packed, 4)
    assert tile is not None
    Y = np.asarray(pallas_dia.pallas_dia_spmm(
        packed.rdata, packed.rmask, X, packed.offsets, packed.shape,
        tile, interpret=False,
    ))
    np.testing.assert_allclose(Y, A.toscipy() @ X, rtol=1e-5, atol=1e-5)


def test_pallas_dia_shift3_variant_on_chip(accel, monkeypatch):
    """The de-aliased input variant (canary-ladder rung 2) lowers and
    matches scipy on the real chip."""
    from legate_sparse_tpu.ops import pallas_dia

    A = _poisson(32)
    dia = A._get_dia()
    dia_data, offsets, mask = dia
    packed = pallas_dia.pack_band(dia_data, offsets, A.shape, mask=mask)
    assert packed is not None
    x = np.linspace(-1.0, 1.0, A.shape[0]).astype(np.float32)
    monkeypatch.setenv("LEGATE_SPARSE_TPU_PALLAS_INPUTS", "distinct")
    pallas_dia.pallas_dia_spmv.clear_cache()
    try:
        y = np.asarray(pallas_dia.pallas_dia_spmv(
            packed.rdata, packed.rmask, x, packed.offsets, packed.shape,
            packed.tile, interpret=False,
        ))
    finally:
        monkeypatch.undo()
        pallas_dia.pallas_dia_spmv.clear_cache()
    np.testing.assert_allclose(y, A.toscipy() @ x, rtol=1e-5, atol=1e-5)


def test_fused_xla_band_fallback_on_chip(accel):
    """The ladder's final rung (dia_spmv_fused) runs on-chip and
    matches scipy — the path the bench lands on if every Pallas
    variant faults."""
    from legate_sparse_tpu.ops import dia_ops

    A = _poisson(32)
    dia = A._get_dia()
    dia_data, offsets, mask = dia
    dpad, mpad = dia_ops.pad_dia(dia_data, offsets, A.shape, mask=mask,
                                 with_mask=mask is not None)
    x = np.linspace(-1.0, 1.0, A.shape[0]).astype(np.float32)
    y = np.asarray(dia_ops.dia_spmv_fused(dpad, mpad, x, offsets,
                                          A.shape))
    np.testing.assert_allclose(y, A.toscipy() @ x, rtol=1e-5, atol=1e-5)


def test_bf16_band_on_chip(accel):
    """bf16 band storage (the bench's TPU-native extension metric)
    dispatches and lands within bf16 tolerance of the f64 reference."""
    import jax.numpy as jnp

    A = _poisson(24, dtype=jnp.bfloat16)
    x = np.linspace(-1.0, 1.0, A.shape[0]).astype(np.float32)
    y = np.asarray(A @ x.astype(jnp.bfloat16)).astype(np.float32)
    y_ref = _poisson(24, dtype=np.float32).toscipy() @ x
    # bf16 has ~3 significant digits; the operator has entries in
    # [-4, 4] and row sums of 0-4.
    np.testing.assert_allclose(y, y_ref, rtol=0.05, atol=0.05)


def test_cg_converges(accel):
    A = _poisson(16)
    b = np.ones(A.shape[0], dtype=np.float32)
    x, info = linalg.cg(A, b, rtol=1e-5, maxiter=2000)
    res = np.linalg.norm(np.asarray(A @ np.asarray(x)) - b)
    assert res < 1e-2 * np.linalg.norm(b)


def test_eigsh_on_chip(accel):
    # The Lanczos scan (matvec chain + reorthogonalization) on chip.
    A = _poisson(16)
    w, _ = linalg.eigsh(A, k=3, which="SA", tol=1e-4)
    import scipy.sparse.linalg as ssl

    w_ref = ssl.eigsh(A.toscipy().astype(np.float64), k=3, which="SA",
                      return_eigenvectors=False)
    np.testing.assert_allclose(np.sort(w), np.sort(w_ref), rtol=1e-3)


def test_minres_on_chip(accel):
    A = _poisson(16)
    b = np.ones(A.shape[0], dtype=np.float32)
    x, _ = linalg.minres(A, b, rtol=1e-5, maxiter=2000)
    res = np.linalg.norm(np.asarray(A @ np.asarray(x)) - b)
    assert res < 1e-2 * np.linalg.norm(b)


def test_expm_multiply_on_chip(accel):
    # Taylor fori_loop chain (SpMV per term) on chip.
    A = _poisson(12)
    L = A * np.float32(-0.05)    # decaying semigroup
    b = np.ones(L.shape[0], dtype=np.float32)
    got = linalg.expm_multiply(L, b)
    import scipy.sparse.linalg as ssl

    ref = ssl.expm_multiply(L.toscipy().astype(np.float64),
                            b.astype(np.float64))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4,
                               atol=1e-5)


def test_connected_components_on_chip(accel):
    # Label-propagation while_loop (scatter-min sweeps) on chip.
    rows = np.array([0, 1, 3, 4])
    cols = np.array([1, 0, 4, 3])
    A = sparse.csr_array((np.ones(4, np.float32), (rows, cols)),
                         shape=(6, 6))
    k, labels = sparse.csgraph.connected_components(A, directed=False)
    assert k == 4
    assert labels[0] == labels[1] and labels[3] == labels[4]
