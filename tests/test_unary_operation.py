# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Zero-preserving unary ufunc family (mirrors reference
``test_unary_operation.py`` over the ``base.py:209-250`` family)."""

import numpy as np
import pytest

import legate_sparse_tpu as sparse
from utils_test.gen import simple_system_gen

UFUNCS = [
    "sin", "tan", "arcsin", "arctan", "sinh", "tanh", "arcsinh",
    "rint", "sign", "expm1", "log1p", "deg2rad", "rad2deg", "floor",
    "ceil", "trunc", "sqrt",
]


@pytest.mark.parametrize("name", UFUNCS)
def test_unary(name):
    a_dense, A, _ = simple_system_gen(9, 7, sparse.csr_array)
    # Inputs are in [0, 1): in-domain for all listed functions.
    result = getattr(A, name)()
    expected = getattr(np, name)(a_dense)
    np.testing.assert_allclose(
        np.asarray(result.todense()), expected, atol=1e-13
    )


def test_arctanh_domain():
    a_dense, A, _ = simple_system_gen(5, 5, sparse.csr_array, tol=0.4)
    np.testing.assert_allclose(
        np.asarray(A.arctanh().todense()), np.arctanh(a_dense), atol=1e-13
    )
