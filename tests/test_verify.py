# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""planverify (tools/verify/): the StableHLO/jaxpr contract gate.

Tier-1 wiring for the verifier itself: rule registry completeness, the
falsifiability drill (every rule must fire on a seeded known-bad
lowered program), the StableHLO-syntax assumptions the parser encodes
revalidated against the live jax, contract coverage of every
registered kernel and plan shape, solver-cycle transfer freedom, the
CLI surface, and — the gate — a full-catalog verify with ZERO
findings against the committed contracts."""

import json
import os
import sys

import numpy as np
import pytest

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from utils_test.tools import load_tool  # noqa: E402

from tools.common.findings import write_baseline  # noqa: E402
from tools.verify import catalog, contracts  # noqa: E402
from tools.verify import hlo as vhlo  # noqa: E402
from tools.verify import rules as vrules  # noqa: E402
from tools.verify.cli import main as cli_main  # noqa: E402
from tools.verify.runner import (  # noqa: E402
    run_verify, select_programs, update_contracts,
)

R = len(jax.devices())
needs_mesh = pytest.mark.skipif(
    R < catalog.MESH_DEVICES,
    reason=f"catalog fixtures lower against the "
           f"{catalog.MESH_DEVICES}-device mesh")

EXPECTED_RULES = {
    "collective-schedule", "comm-bytes", "transfer-freedom",
    "dtype-discipline",
}

# Cheapest program to build: single-shard kernel, no mesh collectives.
CHEAP_PID = "kernel/csr-rowids/spmv/f32"


def _cheap_prog():
    return [catalog.get_program(CHEAP_PID)]


# ------------------------------------------------------------------ #
# registry
# ------------------------------------------------------------------ #

def test_registry_is_complete():
    rules = vrules.all_rules()
    assert set(rules) == EXPECTED_RULES
    for rid, rule in rules.items():
        assert rule.id == rid
        assert rule.description, f"rule {rid} has no description"


def test_duplicate_rule_id_rejected():
    class Dup(vrules.VerifyRule):
        id = "comm-bytes"

    with pytest.raises(ValueError, match="duplicate"):
        vrules.register(Dup)


def test_catalog_ids_unique_and_sourced():
    progs = catalog.all_programs()
    pids = [p.pid for p in progs]
    assert len(pids) == len(set(pids))
    for p in progs:
        assert p.sources, p.pid
        assert "legate_sparse_tpu/obs/comm.py" in p.sources, \
            f"{p.pid}: every program depends on the byte model"


# ------------------------------------------------------------------ #
# the StableHLO parser, on synthetic text (no devices needed)
# ------------------------------------------------------------------ #

_SYNTHETIC = """
  %1 = "stablehlo.collective_permute"(%0) <{channel_handle = \
#stablehlo.channel_handle<handle = 1, type = 1>, source_target_pairs \
= dense<[[0, 1], [1, 2], [2, 2]]> : tensor<3x2xi64>}> : \
(tensor<4xf32>) -> tensor<4xf32>
  %2 = "stablehlo.all_reduce"(%1) <{replica_groups = \
dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>}> ({
  ^bb0(%arg0: tensor<f32>, %arg1: tensor<f32>):
    %3 = stablehlo.add %arg0, %arg1 : tensor<f32>
    stablehlo.return %3 : tensor<f32>
  }) : (tensor<8xf32>) -> tensor<8xf32>
"""


def test_parser_permute_counts_moved_pairs_only():
    permute, reduce = vhlo.parse_collectives(_SYNTHETIC)
    assert permute.kind == "collective_permute"
    assert permute.operand_bytes == 16
    assert permute.n_pairs == 3
    assert permute.moved_pairs == 2      # [2, 2] is a self-pair
    assert reduce.kind == "all_reduce"   # order = program order


def test_parser_skips_reduction_regions():
    # The all_reduce body contains ops and a type signature of its
    # own; the parser must read the OUTER (tensor<8xf32>) operand.
    _, reduce = vhlo.parse_collectives(_SYNTHETIC)
    assert reduce.operand_bytes == 32
    assert reduce.groups == (1, 4)       # 1 group of 4
    assert reduce.model_kind == "psum"


def test_tensor_bytes():
    assert vhlo.tensor_bytes("tensor<2x3xf64>") == 48
    assert vhlo.tensor_bytes("tensor<f32>") == 4
    assert vhlo.tensor_bytes("tensor<8xbf16>") == 16
    with pytest.raises(ValueError):
        vhlo.tensor_bytes("not a tensor")


def test_parser_custom_calls_and_feeds():
    text = ('%0 = stablehlo.custom_call @Sharding(%a) : x\n'
            '"stablehlo.custom_call"(%b) <{call_target_name = '
            '"xla_python_cpu_callback"}> : y\n'
            '%1 = "stablehlo.outfeed"(%c) : z\n')
    assert vhlo.parse_custom_calls(text) == [
        "Sharding", "xla_python_cpu_callback"]
    assert vhlo.parse_feeds(text) == ["outfeed"]


@needs_mesh
def test_stablehlo_syntax_assumptions_hold():
    """Revalidate the quoted-generic-form assumption against the live
    jax: a real lowered psum must parse into exactly the collective
    the ledger prices, byte-exactly."""
    from legate_sparse_tpu.obs import comm

    built = vrules._psum_built()
    ops = vhlo.parse_collectives(built.hlo)
    assert [o.kind for o in ops] == ["all_reduce"]
    assert ops[0].groups == (1, R)
    assert vrules.lowered_volumes(built) == {
        "psum": comm.psum_bytes(1, 4, R)}
    assert vhlo.host_callbacks(built.jaxpr) == []


# ------------------------------------------------------------------ #
# falsifiability drill: every rule must fire on its known-bad program
# ------------------------------------------------------------------ #

@needs_mesh
@pytest.mark.parametrize("rule_id", sorted(EXPECTED_RULES))
def test_rule_is_falsifiable(rule_id):
    findings = vrules.get_rule(rule_id).falsifiability()
    assert findings, f"rule {rule_id} produced no finding on its " \
                     f"known-bad program — it checks nothing"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.message for f in findings)


# ------------------------------------------------------------------ #
# contract coverage: registry kernels + plan shapes -> committed files
# ------------------------------------------------------------------ #

def test_contract_filename_scheme():
    assert contracts.contract_name("dist/spmv/1d-row/halo/f32") == \
        "dist-spmv-1d-row-halo-f32.json"
    assert contracts.kernel_prefix("csr-rowids") == "kernel-csr-rowids-"
    assert contracts.dist_prefix(("dist_spmv", "1d-row", "halo")) == \
        "dist-spmv-1d-row-halo"


def test_every_catalog_program_has_committed_contract():
    for p in catalog.all_programs():
        c = contracts.load_contract(p.pid)
        assert c is not None, f"{p.pid}: no committed contract"
        assert c["version"] == contracts.CONTRACT_VERSION
        assert c["program"] == p.pid
        assert c["reason"].strip()


def test_every_kernel_label_and_plan_shape_is_contracted():
    from legate_sparse_tpu.parallel.dist_csr import DIST_PLAN_SHAPES
    from legate_sparse_tpu.parallel.dist_spgemm import (
        SPGEMM_PLAN_SHAPES,
    )
    from tools.lint.core import Context
    from tools.lint.rules.plan_contract import registry_labels

    names = contracts.list_contracts()
    labels = registry_labels(Context())
    assert labels
    for label in labels:
        prefix = contracts.kernel_prefix(label)
        assert any(n.startswith(prefix) for n in names), label
    for triple in tuple(DIST_PLAN_SHAPES) + tuple(SPGEMM_PLAN_SHAPES):
        prefix = contracts.dist_prefix(triple) + "-"
        assert any(n.startswith(prefix) for n in names), triple
    # The acceptance floor, spelled out: spmv/cg/spgemm contracted on
    # BOTH 1-d-row and 2-d-block.
    for req in ("dist-spmv-1d-row-halo", "dist-spmv-2d-block-panel",
                "dist-cg-1d-row-halo", "dist-cg-2d-block-panel",
                "dist-spgemm-1d-row-all-gather",
                "dist-spgemm-2d-block-panel"):
        assert any(n.startswith(req) for n in names), req


# ------------------------------------------------------------------ #
# solver cycles: lowered loop bodies are host-transfer-free
# ------------------------------------------------------------------ #

@needs_mesh
@pytest.mark.parametrize("pid", ["dist/cg/1d-row/halo/f32",
                                 "dist/cg/2d-block/panel/f32"])
def test_cg_body_is_transfer_free(pid):
    built = catalog.build(pid)
    assert vrules.transfer_violations(built) == []
    assert vhlo.host_callbacks(built.jaxpr) == []
    c = contracts.load_contract(pid)
    assert c["transfer_free"] is True
    # The body's scalar psums are partitioner-inserted, priced as
    # deferred volumes — never as host round-trips.
    assert c["deferred_volumes"].get("psum", 0) > 0


@needs_mesh
def test_gmres_cycle_loops_without_host_transfers():
    pid = "dist/gmres/1d-row/halo/f32"
    built = catalog.build(pid)
    # The Arnoldi loop is really in the traced program (so the
    # transfer-freedom claim is about a genuine per-iteration body)...
    prims = {e.primitive.name for e, _ in vhlo.iter_eqns(built.jaxpr)}
    assert prims & vhlo.LOOP_PRIMS
    # ...and nothing in or around it round-trips to the host.
    assert vrules.transfer_violations(built) == []
    c = contracts.load_contract(pid)
    assert c["transfer_free"] is True
    # Loop-replayed collectives: per-dispatch bytes are not a
    # lower-time quantity, so the contract records no prediction.
    assert c["predicted_volumes"] is None
    assert c["notes"].get("loops") is True


# ------------------------------------------------------------------ #
# drift detection + baseline lifecycle (temp contract dirs)
# ------------------------------------------------------------------ #

@needs_mesh
def test_missing_contract_is_a_finding(tmp_path):
    res = run_verify(programs=_cheap_prog(), baseline_path=None,
                     contracts_dir=str(tmp_path / "empty"))
    assert res.exit_code == 1
    assert [f.rule for f in res.active] == ["collective-schedule"]
    assert "no committed contract" in res.active[0].message


@needs_mesh
def test_bytes_drift_fires_then_baselines_then_goes_stale(tmp_path):
    payload = contracts.load_contract(CHEAP_PID)
    drifted = dict(payload, lowered_volumes={"psum": 12345})
    cdir = str(tmp_path / "contracts")
    contracts.write_contract(CHEAP_PID, drifted, cdir)

    res = run_verify(programs=_cheap_prog(), baseline_path=None,
                     contracts_dir=cdir)
    assert res.exit_code == 1
    assert {f.rule for f in res.active} == {"comm-bytes"}

    bl = str(tmp_path / "baseline.json")
    write_baseline(bl, res.active)
    res2 = run_verify(programs=_cheap_prog(), baseline_path=bl,
                      contracts_dir=cdir)
    assert res2.exit_code == 0
    assert res2.baselined and not res2.active
    assert res2.stale_baseline == []

    # Against the healthy committed contract the grandfathered entry
    # matches nothing — reported stale so the baseline shrinks.
    res3 = run_verify(programs=_cheap_prog(), baseline_path=bl)
    assert res3.exit_code == 0
    assert res3.stale_baseline


@needs_mesh
def test_schedule_drift_reports_first_divergence(tmp_path):
    payload = contracts.load_contract(CHEAP_PID)
    phantom = {"kind": "all_gather", "operand_bytes": 64,
               "moved_pairs": None, "groups": [1, 8], "bytes": 448}
    cdir = str(tmp_path / "contracts")
    contracts.write_contract(
        CHEAP_PID, dict(payload, schedule=[phantom]), cdir)
    res = run_verify(programs=_cheap_prog(), baseline_path=None,
                     contracts_dir=cdir)
    scheds = [f for f in res.active if f.rule == "collective-schedule"]
    assert len(scheds) == 1
    assert "missing op: all_gather" in scheds[0].message


@needs_mesh
def test_update_contracts_is_deterministic(tmp_path):
    p1 = update_contracts("probe", programs=_cheap_prog(),
                          contracts_dir=str(tmp_path / "a"))
    p2 = update_contracts("probe", programs=_cheap_prog(),
                          contracts_dir=str(tmp_path / "b"))
    with open(p1[0]) as f1, open(p2[0]) as f2:
        assert f1.read() == f2.read()
    with open(p1[0]) as f:
        fresh = json.load(f)
    committed = contracts.load_contract(CHEAP_PID)
    strip = lambda d: {k: v for k, v in d.items() if k != "reason"}
    assert strip(fresh) == strip(committed)


def test_update_contracts_requires_reason():
    with pytest.raises(ValueError, match="reason"):
        update_contracts("  ", programs=[])


# ------------------------------------------------------------------ #
# --changed selection
# ------------------------------------------------------------------ #

def test_changed_selection_maps_files_to_programs():
    all_ids = {p.pid for p in catalog.all_programs()}
    # Verifier / shared-model edits re-verify everything.
    got = select_programs(selection=["tools/verify/hlo.py"])
    assert {p.pid for p in got} == all_ids
    got = select_programs(selection=["legate_sparse_tpu/obs/comm.py"])
    assert {p.pid for p in got} == all_ids
    # Unrelated files select nothing.
    assert select_programs(selection=["README.md"]) == []
    # A solver-only module re-verifies exactly the solver programs.
    got = {p.pid for p in select_programs(
        selection=["legate_sparse_tpu/linalg.py"])}
    assert got
    assert all(i.startswith(("dist/cg/", "dist/gmres/")) for i in got)


def test_unknown_program_id_raises():
    with pytest.raises(KeyError, match="no-such-program"):
        select_programs(program_ids=["no-such-program"])


# ------------------------------------------------------------------ #
# CLI surface
# ------------------------------------------------------------------ #

def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in EXPECTED_RULES:
        assert rid in out


def test_cli_list_programs(capsys):
    assert cli_main(["--list-programs"]) == 0
    out = capsys.readouterr().out
    assert CHEAP_PID in out
    assert "dist/cg/2d-block/panel/f32" in out


def test_cli_usage_errors(capsys):
    assert cli_main(["--rules", "no-such-rule"]) == 2
    assert "unknown rule" in capsys.readouterr().err
    assert cli_main(["no/such/program"]) == 2
    assert "unknown program" in capsys.readouterr().err
    assert cli_main(["--update-contracts", CHEAP_PID]) == 2
    assert "--reason" in capsys.readouterr().err


@needs_mesh
def test_cli_json_artifact_single_program(capsys):
    rc = cli_main([CHEAP_PID, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["tool"] == "planverify"
    assert data["findings"] == []
    assert data["exit_code"] == 0
    assert data["programs_checked"] == [CHEAP_PID]
    assert set(data["rules_run"]) == EXPECTED_RULES


# ------------------------------------------------------------------ #
# doctor ingestion: planverify --json is the fourth artifact kind
# ------------------------------------------------------------------ #

@needs_mesh
def test_doctor_ingests_planverify_artifact(tmp_path):
    doctor = load_tool("doctor")
    art = run_verify(programs=_cheap_prog()).to_json()
    path = tmp_path / "pv.json"
    path.write_text(json.dumps(art))
    ev = doctor.Evidence()
    assert doctor.load_artifact(str(path), ev) == "planverify"
    assert doctor.diagnose(ev) == []          # clean run: no findings

    art["findings"] = [{
        "rule": "comm-bytes", "path": "tools/verify/contracts/x.json",
        "line": 0, "message": "lowered volumes diverge",
        "severity": "error"}]
    path.write_text(json.dumps(art))
    ev = doctor.Evidence()
    doctor.load_artifact(str(path), ev)
    findings = doctor.diagnose(ev)
    drift = [f for f in findings if f["code"] == "plan-contract-drift"]
    assert len(drift) == 1
    assert drift[0]["severity"] == "critical"
    assert "--update-contracts" in drift[0]["hint"]
    assert doctor.main([str(path), "--check"]) == 1


# ------------------------------------------------------------------ #
# tier-1 gate: the whole catalog verifies clean against the committed
# contracts — collective schedules, byte volumes (exact), transfer
# freedom and dtype discipline, for every kernel and dist plan shape
# ------------------------------------------------------------------ #

@needs_mesh
def test_full_catalog_verify_is_clean():
    res = run_verify()
    assert res.active == [], "findings:\n" + "\n".join(
        f.render() for f in res.active)
    assert res.stale_baseline == []
    assert set(res.rules_run) == EXPECTED_RULES
    assert sorted(res.programs_checked) == sorted(
        p.pid for p in catalog.all_programs())
