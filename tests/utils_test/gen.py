# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Test matrix generators (role of reference ``tests/integration/utils/``:
banded and seeded-random fixtures for differential testing vs scipy)."""

import numpy as np
import scipy.sparse as scsp


def banded_matrix(n: int, nnz_per_row: int, dtype=np.float64):
    """Banded scipy CSR with nnz_per_row diagonals (odd), values 1..k."""
    assert nnz_per_row % 2 == 1
    half = nnz_per_row // 2
    offsets = list(range(-half, half + 1))
    diagonals = [
        np.full(n - abs(off), float(off + half + 1), dtype=dtype)
        for off in offsets
    ]
    return scsp.diags(diagonals, offsets, shape=(n, n), format="csr",
                      dtype=dtype)


def random_csr(n: int, m: int, density: float, seed: int, dtype=np.float64):
    rng = np.random.default_rng(seed)
    mat = scsp.random(
        n, m, density=density, format="csr", dtype=np.float64,
        random_state=np.random.RandomState(seed),
        data_rvs=rng.standard_normal,
    )
    return mat.astype(dtype)


def random_dense(n: int, m: int, density: float, seed: int):
    return np.asarray(random_csr(n, m, density, seed).todense())


def random_vector(n: int, seed: int):
    return np.random.default_rng(seed).standard_normal(n)


def simple_system_gen(n, m, cls, tol=0.5, seed=0):
    """Thresholded random dense + its sparse version + a vector
    (same contract as reference ``sample.py:49-55``)."""
    rng = np.random.default_rng(seed)
    a_dense = rng.random((n, m))
    x = rng.random(m)
    a_dense = np.where(a_dense < tol, a_dense, 0.0)
    a_sparse = None if cls is None else cls(a_dense)
    return a_dense, a_sparse, x


def spd_system(n: int, density: float, seed: int):
    """SPD matrix A + rhs (same construction as reference
    ``test_cg_solve.py:23-35``: symmetrized random + N·I)."""
    A = random_dense(n, n, density, seed)
    A = 0.5 * (A + A.T)
    A = A + n * np.eye(n)
    x = random_vector(n, seed + 1)
    return A, x
