# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""One rank of the multi-process distributed lane.

Launched by ``tests/test_multiprocess.py`` (and ``test.py --multiproc``)
as N separate OS processes, each owning 4 virtual CPU devices, joined
through ``parallel.mesh.init_distributed`` — the honest analog of the
reference's multi-rank launches (reference ``test.py:24-32`` legate
resource shapes): the mesh spans processes, so every psum/ppermute in
the dist kernels crosses a real process boundary through the
distributed runtime instead of staying inside one XLA client.

Usage: python multiproc_worker.py <process_id> <num_processes> <port> [N] [ext]
(``ext`` adds the GMG hierarchy and dist_gmres across ranks)
Prints ``MULTIPROC-OK <pid>`` on success; any failure exits non-zero.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]
N = int(sys.argv[4]) if len(sys.argv) > 4 else 16
WITH_EXT = len(sys.argv) > 5 and sys.argv[5] in ("ext", "gmg")

# Environment must be fixed before jax initializes any backend.  A
# parent test lane may already carry a device-count pin in XLA_FLAGS
# (conftest's pin_cpu); strip it rather than appending a duplicate
# flag whose resolution order is undocumented.
import re

_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=4"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from legate_sparse_tpu.parallel.mesh import init_distributed  # noqa: E402

# The one network bootstrap (reference: GASNet/UCX/MPI selection).
init_distributed(f"localhost:{port}", num_processes=nproc, process_id=pid)

import numpy as np  # noqa: E402
import scipy.sparse as sp  # noqa: E402

import legate_sparse_tpu as sparse  # noqa: E402
from legate_sparse_tpu.parallel.dist_csr import (  # noqa: E402
    dist_cg, dist_spmv, shard_csr, shard_vector,
)
from legate_sparse_tpu.parallel.mesh import make_row_mesh  # noqa: E402

assert len(jax.devices()) == 4 * nproc, (
    f"expected {4 * nproc} global devices, got {len(jax.devices())}"
)
assert len(jax.local_devices()) == 4

# Every rank builds the same global operator host-side (default tiny:
# this lane proves cross-process collectives; the slow lane passes a
# larger N so halo/padding budgets see a non-trivial shape).
n = N * N
main = np.full(n, 4.0)
off1 = np.full(n - 1, -1.0)
off1[np.arange(1, N) * N - 1] = 0.0
offn = np.full(n - N, -1.0)
diags_args = ([main, off1, off1, offn, offn], [0, 1, -1, N, -N])
A = sparse.diags(*diags_args, shape=(n, n), format="csr")
S = sp.diags(*diags_args, shape=(n, n), format="csr")

mesh = make_row_mesh()          # all 8 devices, spanning both ranks
dA = shard_csr(A, mesh=mesh)

rng = np.random.default_rng(5)
x = rng.normal(size=n)
xs = shard_vector(x, mesh, dA.rows_padded)
y = dist_spmv(dA, xs)
ref = S @ x

# Each rank checks ITS OWN addressable shards against the scipy
# reference — the only data a rank can see without extra collectives.
rows_padded = dA.rows_padded
for shard in y.addressable_shards:
    lo = shard.index[0].start or 0
    got = np.asarray(shard.data).reshape(-1)
    hi = min(lo + got.shape[0], n)
    if lo < n:
        np.testing.assert_allclose(
            got[: hi - lo], ref[lo:hi], rtol=1e-10, atol=1e-12,
            err_msg=f"rank {pid} shard rows [{lo}, {hi})",
        )

# Whole-solve path: dist CG to tolerance (psum reductions cross the
# process boundary every iteration block).
b = np.ones(n)
sol, iters = dist_cg(dA, b, rtol=1e-10)
# The true residual needs the full solution; gather it with one
# replicated resharding (cross-process data movement is exactly what
# this lane exists to prove).
from jax.sharding import NamedSharding, PartitionSpec  # noqa: E402

sol_rep = jax.device_put(
    sol, NamedSharding(mesh, PartitionSpec())
)
sol_np = np.asarray(sol_rep).reshape(-1)[:n]
rnorm = np.linalg.norm(b - S @ sol_np)
assert rnorm <= 1e-7 * np.linalg.norm(b), f"rank {pid}: ||r|| = {rnorm}"

# dist SpGEMM: a second collective family crossing processes.  The
# product is verified through a distributed matvec against scipy on
# THIS rank's addressable shards (a host gather of a process-spanning
# array is not possible, by design).
from legate_sparse_tpu.parallel.dist_spgemm import dist_spgemm  # noqa: E402

dC = dist_spgemm(dA, dA)
yC = dist_spmv(dC, xs)
refC = (S @ S) @ x
for shard in yC.addressable_shards:
    lo = shard.index[0].start or 0
    got = np.asarray(shard.data).reshape(-1)
    hi = min(lo + got.shape[0], n)
    if lo < n:
        np.testing.assert_allclose(
            got[: hi - lo], refC[lo:hi], rtol=1e-9, atol=1e-9,
            err_msg=f"rank {pid} dist_spgemm@x rows [{lo}, {hi})",
        )

if WITH_EXT:
    # Geometric multigrid across ranks: the Galerkin R@A@P hierarchy
    # build chains dist_spgemm products over the process-spanning
    # mesh, and each V-cycle smooth/restrict/prolong crosses ranks.
    from legate_sparse_tpu.parallel import DistGMG  # noqa: E402
    from legate_sparse_tpu.parallel.dist_build import dist_poisson2d  # noqa: E402

    dP = dist_poisson2d(N, mesh=mesh)
    gmg = DistGMG(dP, levels=2)
    bg = np.ones(n)
    solg, itg = dist_cg(dP, bg, M=gmg.cycle, rtol=1e-10)
    solg_rep = jax.device_put(
        solg, NamedSharding(mesh, PartitionSpec()))
    xg = np.asarray(solg_rep).reshape(-1)[:n]
    # dist_poisson2d builds the same 5-point operator as S above.
    rg = np.linalg.norm(bg - S @ xg)
    assert rg <= 1e-7 * np.linalg.norm(bg), f"rank {pid} gmg ||r||={rg}"

    # Non-symmetric solver across ranks (Arnoldi inner products are
    # psums over the spanning mesh).
    from legate_sparse_tpu.parallel.dist_csr import dist_gmres  # noqa: E402

    solr, _ = dist_gmres(dA, b, rtol=1e-10)
    solr_rep = jax.device_put(
        solr, NamedSharding(mesh, PartitionSpec()))
    xr = np.asarray(solr_rep).reshape(-1)[:n]
    rr = np.linalg.norm(b - S @ xr)
    assert rr <= 1e-6 * np.linalg.norm(b), f"rank {pid} gmres ||r||={rr}"

    # Symmetric-indefinite + non-symmetric-stabilized solvers and the
    # distributed Lanczos across ranks.
    from legate_sparse_tpu.parallel.dist_csr import (  # noqa: E402
        dist_bicgstab, dist_eigsh, dist_minres,
    )

    solb, _ = dist_bicgstab(dA, b, rtol=1e-10)
    solb_rep = jax.device_put(
        solb, NamedSharding(mesh, PartitionSpec()))
    xb = np.asarray(solb_rep).reshape(-1)[:n]
    rb = np.linalg.norm(b - S @ xb)
    assert rb <= 1e-6 * np.linalg.norm(b), f"rank {pid} bicgstab ||r||={rb}"

    solm, _ = dist_minres(dA, b, rtol=1e-10)
    solm_rep = jax.device_put(
        solm, NamedSharding(mesh, PartitionSpec()))
    xm = np.asarray(solm_rep).reshape(-1)[:n]
    rm = np.linalg.norm(b - S @ xm)
    assert rm <= 1e-6 * np.linalg.norm(b), f"rank {pid} minres ||r||={rm}"

    # The top Poisson eigenvalues cluster ~0.1 apart; a larger
    # subspace resolves them (same requirement as scipy ncv).
    w = np.asarray(dist_eigsh(dA, k=3, which="LA", ncv=48,
                              return_eigenvectors=False))
    import scipy.sparse.linalg as _ssl
    w_ref = _ssl.eigsh(S.tocsc().astype(np.float64), k=3, which="LA",
                       return_eigenvectors=False)
    np.testing.assert_allclose(sorted(w), sorted(w_ref), rtol=1e-8,
                               err_msg=f"rank {pid} dist_eigsh")

print(f"MULTIPROC-OK {pid} iters={int(iters)} rnorm={rnorm:.2e}",
      flush=True)
