# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Shared loader for the repo's ``tools/`` CLIs.

Imports a tools/ script in-process (a subprocess would re-import the
whole package — seconds of suite wall time for nothing).  One home
instead of a per-test-file copy, so tool-loading changes happen once.
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_tool(name):
    """Import ``tools/<name>.py`` as a fresh module object."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
