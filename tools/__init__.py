# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
# Package marker so ``tools.lint`` (sparselint) is importable from the
# repo root; the single-file CLIs in this directory stay runnable as
# plain scripts and loadable via tests/utils_test/tools.load_tool.
