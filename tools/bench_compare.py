#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Diff bench JSONs field-by-field with measured noise bands — the
perf-regression gate over the ``BENCH_r0*.json`` trajectory.

The archived bench artifacts were never machine-compared, so "perf
asserted, not demonstrated" could silently recur between rounds.  This
tool gates it: ``*_ms`` (lower is better), ``*_roofline_ratio``
(higher is better) and ``*_comm_bytes`` (deterministic interconnect
predictions) are compared with a noise band derived from the recorded
``stream_samples`` spread of both runs, and the exit status is nonzero
on any out-of-band regression — or on a gated field that vanished from
the newer run (the key-superset contract in BASELINE.md).

Usage::

    # explicit pair (old, new) — any artifact shape: driver wrapper
    # {"parsed": ...}, raw bench JSON, or a log whose last line is one
    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json

    # the whole trajectory: renders the table over BENCH_r0*.json in
    # DIR (default .) and gates newest vs previous
    python tools/bench_compare.py --trajectory
    python tools/bench_compare.py --trajectory --dir /path/to/repo

    # restrict the gate (e.g. deterministic fields only for a
    # cross-machine golden comparison)
    python tools/bench_compare.py golden.json new.json \
        --fields '*_comm_bytes,dist_shards,schema_version'

Knobs: ``--band-mult`` scales the stream-spread noise band (default
3.0), ``--floor`` floors it for runs without spread data (default
0.25), ``--comm-tol`` is the fixed tolerance for byte predictions
(default 0.01), ``--allow-missing`` downgrades vanished fields to
informational.  Exit status: 0 clean, 1 regression(s)/missing gated
fields, 2 usage or unreadable input.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

from legate_sparse_tpu.obs import regress  # noqa: E402


def _gate(old, new, args) -> int:
    fields = ([p.strip() for p in args.fields.split(",") if p.strip()]
              if args.fields else None)
    findings = regress.compare(
        old, new, band_mult=args.band_mult, floor=args.floor,
        comm_tol=args.comm_tol, fields=fields,
        allow_missing=args.allow_missing,
    )
    band = regress.noise_band(old, new, floor=args.floor)
    print(regress.render_findings(findings, band=band))
    bad = regress.regressions(findings)
    if bad:
        print(f"\nREGRESSED: {len(bad)} field(s): "
              + ", ".join(f["field"] for f in bad), file=sys.stderr)
        return 1
    print("\nclean: no out-of-band regressions")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Bench JSON regression gate / trajectory table.")
    ap.add_argument("old", nargs="?", help="older bench artifact")
    ap.add_argument("new", nargs="?", help="newer bench artifact")
    ap.add_argument("--trajectory", action="store_true",
                    help="render the BENCH_r0*.json trajectory table "
                         "and gate newest vs previous")
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r0*.json "
                         "(trajectory mode; default .)")
    ap.add_argument("--band-mult", type=float,
                    default=regress.DEFAULT_BAND_MULT,
                    help="noise-band multiplier on the stream-sample "
                         "spread (default %(default)s)")
    ap.add_argument("--floor", type=float, default=regress.DEFAULT_FLOOR,
                    help="relative noise-band floor (default "
                         "%(default)s)")
    ap.add_argument("--comm-tol", type=float, default=regress.COMM_TOL,
                    help="tolerance for *_comm_bytes fields (default "
                         "%(default)s)")
    ap.add_argument("--fields", default=None,
                    help="comma-separated fnmatch patterns restricting "
                         "the gated fields")
    ap.add_argument("--allow-missing", action="store_true",
                    help="vanished gated fields are informational, "
                         "not failures")
    args = ap.parse_args(argv)

    if args.trajectory:
        paths = sorted(glob.glob(os.path.join(args.dir,
                                              "BENCH_r[0-9]*.json")))
        if not paths:
            print(f"{args.dir}: no BENCH_r*.json artifacts",
                  file=sys.stderr)
            return 2
        rounds, labels = [], []
        for p in paths:
            try:
                rounds.append(regress.load_bench(p))
                labels.append(os.path.basename(p)
                              .replace("BENCH_", "").replace(".json",
                                                             ""))
            except (OSError, ValueError) as e:
                print(f"skipping {p}: {e}", file=sys.stderr)
        if not rounds:
            return 2
        print(regress.render_trajectory(rounds, labels))
        if len(rounds) < 2:
            return 0
        print(f"\ngate: {labels[-2]} -> {labels[-1]}")
        return _gate(rounds[-2], rounds[-1], args)

    if not (args.old and args.new):
        ap.print_usage(sys.stderr)
        return 2
    try:
        old = regress.load_bench(args.old)
        new = regress.load_bench(args.new)
    except (OSError, ValueError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    return _gate(old, new, args)


if __name__ == "__main__":
    sys.exit(main())
