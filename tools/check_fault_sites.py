#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Static fault-site coverage check (tier-1 via tests/test_resilience).

Injection coverage rots silently: a refactor that renames or drops a
``fault_point("...")`` call leaves the catalog advertising a site that
no longer exists, and the drills that "cover" it keep passing because
they arm a hook nobody calls.  This pass makes the three views of the
site list — the code's literals, ``resilience.faults.CATALOG``, and
the ``docs/RESILIENCE.md`` site table — agree, and fails on any drift:

1. every site literal passed to ``fault_point(`` / ``guarded_call(`` /
   ``policy.run(`` in ``legate_sparse_tpu/`` must be in the catalog
   (no unregistered sites);
2. every catalog site must appear as a quoted literal somewhere in
   the package OUTSIDE the catalog's own module (no orphaned catalog
   entries — the rot case; ``faults.py`` itself is excluded because
   the catalog defines every site as a quoted literal there, which
   would make this rule unfalsifiable);
3. every catalog site must appear in ``docs/RESILIENCE.md`` (the
   operator-facing list stays complete);
4. every site in the chaos drill's default pool
   (``resilience.chaos.DEFAULT_SITES``) must be a catalog site — a
   drill that arms an unregistered name silently tests nothing.

Usage::

    python tools/check_fault_sites.py          # check, exit 0/1
    python tools/check_fault_sites.py --list   # print the catalog
"""

from __future__ import annotations

import argparse
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from legate_sparse_tpu.resilience.chaos import DEFAULT_SITES  # noqa: E402
from legate_sparse_tpu.resilience.faults import CATALOG  # noqa: E402

PKG_DIR = os.path.join(_REPO, "legate_sparse_tpu")
DOC_PATH = os.path.join(_REPO, "docs", "RESILIENCE.md")

# A quoted dotted lowercase name passed as the first argument of one
# of the site-taking entry points.  ``\brun\(`` deliberately also
# matches ``policy.run(``/``_rpolicy.run(``; the dotted-name shape
# keeps unrelated ``run(`` calls (subprocess etc.) out.
SITE_CALL_RE = re.compile(
    r"(?:fault_point|guarded_call|_resil_guarded|\brun)\(\s*\n?\s*"
    r"[\"']([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)[\"']")


def _py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def collect_call_sites(root: str = PKG_DIR):
    """{site: [relpath, ...]} for every site literal at an entry
    point, plus {site: count} of raw quoted occurrences anywhere."""
    calls = {}
    quoted = {}
    for path in _py_files(root):
        with open(path) as f:
            text = f.read()
        rel = os.path.relpath(path, _REPO)
        for site in SITE_CALL_RE.findall(text):
            calls.setdefault(site, []).append(rel)
        if rel.replace(os.sep, "/") == (
                "legate_sparse_tpu/resilience/faults.py"):
            # The catalog's own module quotes every site by
            # definition; counting it would make orphan detection
            # (rule 2) unable to ever fire.
            continue
        for site in CATALOG:
            if f'"{site}"' in text or f"'{site}'" in text:
                quoted[site] = quoted.get(site, 0) + 1
    return calls, quoted


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-check fault-point call sites against the "
                    "resilience catalog and docs.")
    ap.add_argument("--list", action="store_true",
                    help="print the catalog with call-site locations")
    args = ap.parse_args(argv)

    calls, quoted = collect_call_sites()
    problems = []

    unregistered = sorted(set(calls) - set(CATALOG))
    for site in unregistered:
        problems.append(
            f"call site uses unregistered name {site!r} "
            f"(in {', '.join(sorted(set(calls[site])))}) — add it to "
            f"resilience.faults.CATALOG")

    orphaned = sorted(s for s in CATALOG if not quoted.get(s))
    for site in orphaned:
        problems.append(
            f"catalog site {site!r} has NO call-site literal in the "
            f"package — injection coverage rotted")

    try:
        with open(DOC_PATH) as f:
            doc = f.read()
    except OSError as e:
        doc = ""
        problems.append(f"docs/RESILIENCE.md unreadable: {e}")
    undocumented = sorted(s for s in CATALOG if s not in doc)
    for site in undocumented:
        problems.append(
            f"catalog site {site!r} missing from docs/RESILIENCE.md")

    for site in sorted(set(DEFAULT_SITES) - set(CATALOG)):
        problems.append(
            f"chaos.DEFAULT_SITES entry {site!r} is not a catalog "
            f"site — the drill would arm a hook nobody calls")

    if args.list:
        width = max(len(s) for s in CATALOG)
        for site in sorted(CATALOG):
            where = ", ".join(sorted(set(calls.get(site, [])))) or "-"
            print(f"{site.ljust(width)}  {where}")

    if problems:
        for p in problems:
            print(f"check_fault_sites: {p}", file=sys.stderr)
        print(f"check_fault_sites: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    if not args.list:
        print(f"check_fault_sites: OK — {len(CATALOG)} sites, all "
              f"wired and documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
