#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Static fault-site coverage check (tier-1 via tests/test_resilience).

Thin back-compat wrapper: the analysis now lives in the sparselint
``fault-sites`` rule (``tools/lint/rules/fault_sites.py``; run the
whole suite with ``python tools/sparselint.py``).  This CLI keeps the
legacy entry point, flags, message wording and exit semantics.

Injection coverage rots silently: a refactor that renames or drops a
``fault_point("...")`` call leaves the catalog advertising a site that
no longer exists, and the drills that "cover" it keep passing because
they arm a hook nobody calls.  The pass makes the three views of the
site list — the code's literals, ``resilience.faults.CATALOG``, and
the ``docs/RESILIENCE.md`` site table — agree, and fails on any drift
(unregistered call-site names, orphaned catalog entries, undocumented
sites, chaos-pool entries outside the catalog).

Usage::

    python tools/check_fault_sites.py          # check, exit 0/1
    python tools/check_fault_sites.py --list   # print the catalog
"""

from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from legate_sparse_tpu.resilience.chaos import DEFAULT_SITES  # noqa: E402
from legate_sparse_tpu.resilience.faults import CATALOG  # noqa: E402

from tools.lint.rules.fault_sites import (  # noqa: E402
    SITE_CALL_RE, collect_call_sites, problems_for)

__all__ = ["CATALOG", "DEFAULT_SITES", "SITE_CALL_RE",
           "collect_call_sites", "main"]

PKG_DIR = os.path.join(_REPO, "legate_sparse_tpu")
DOC_PATH = os.path.join(_REPO, "docs", "RESILIENCE.md")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-check fault-point call sites against the "
                    "resilience catalog and docs.")
    ap.add_argument("--list", action="store_true",
                    help="print the catalog with call-site locations")
    args = ap.parse_args(argv)

    # Read the module globals at call time (not via early-bound
    # defaults) so tests can monkeypatch CATALOG/PKG_DIR/DOC_PATH.
    pairs, calls = problems_for(CATALOG, DEFAULT_SITES, PKG_DIR,
                                DOC_PATH, _REPO)
    problems = [msg for msg, _rel in pairs]

    if args.list:
        width = max(len(s) for s in CATALOG)
        for site in sorted(CATALOG):
            where = ", ".join(sorted(set(calls.get(site, [])))) or "-"
            print(f"{site.ljust(width)}  {where}")

    if problems:
        for p in problems:
            print(f"check_fault_sites: {p}", file=sys.stderr)
        print(f"check_fault_sites: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    if not args.list:
        print(f"check_fault_sites: OK — {len(CATALOG)} sites, all "
              f"wired and documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
