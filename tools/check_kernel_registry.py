#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Static kernel-registry coverage check (tier-1 via tests/test_autotune).

The autotune candidate registry rots silently: a kernel rename in
``ops/spmv.py`` leaves ``autotune/registry.py`` advertising an entry
point that no longer exists (the harness would only notice at measure
time, and routing would error mid-dispatch), and a dropped dispatch
label leaves verdicts that can never be served.  This pass makes the
three views of the candidate list — the registry, the package's
dispatch literals, and the ``docs/AUTOTUNER.md`` candidate table —
agree, and fails on any drift:

1. every ``Candidate.kernel`` must exist as a callable in
   ``legate_sparse_tpu.ops.spmv`` AND its ``trace.<kernel>`` compile
   counter must be bumped somewhere in the package (the
   instrumentation contract every jitted kernel follows);
2. every candidate label must appear as a quoted literal somewhere in
   the package OUTSIDE the registry's own module (no orphaned
   candidates — ``registry.py`` itself is excluded because it defines
   every label as a quoted literal, which would make this rule
   unfalsifiable);
3. every candidate label must appear in ``docs/AUTOTUNER.md`` (the
   operator-facing candidate table stays complete);

plus the structural invariant that each ``CANDIDATES`` dict key equals
its entry's ``label`` (verdicts store labels; a mismatched key would
make a recorded verdict unroutable).

Usage::

    python tools/check_kernel_registry.py          # check, exit 0/1
    python tools/check_kernel_registry.py --list   # print the registry
"""

from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from legate_sparse_tpu.autotune.registry import CANDIDATES  # noqa: E402
from legate_sparse_tpu.ops import spmv as _spmv  # noqa: E402

PKG_DIR = os.path.join(_REPO, "legate_sparse_tpu")
DOC_PATH = os.path.join(_REPO, "docs", "AUTOTUNER.md")
REGISTRY_REL = "legate_sparse_tpu/autotune/registry.py"


def _py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def collect_literals(root: str = PKG_DIR):
    """{label: [relpath, ...]} of quoted label occurrences outside the
    registry module, plus {kernel: True} for packages quoting the
    ``trace.<kernel>`` counter name."""
    quoted = {}
    traced = {}
    trace_names = {c.kernel: f"trace.{c.kernel}"
                   for c in CANDIDATES.values()}
    for path in _py_files(root):
        with open(path) as f:
            text = f.read()
        rel = os.path.relpath(path, _REPO).replace(os.sep, "/")
        for kernel, tname in trace_names.items():
            if f'"{tname}"' in text or f"'{tname}'" in text:
                traced[kernel] = True
        if rel == REGISTRY_REL:
            # The registry quotes every label by definition; counting
            # it would make orphan detection (rule 2) unable to fire.
            continue
        for label in CANDIDATES:
            if f'"{label}"' in text or f"'{label}'" in text:
                quoted.setdefault(label, []).append(rel)
    return quoted, traced


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-check the autotune candidate registry "
                    "against ops/spmv.py kernels, dispatch-site "
                    "literals and docs.")
    ap.add_argument("--list", action="store_true",
                    help="print the registry with dispatch-site "
                         "locations")
    args = ap.parse_args(argv)

    quoted, traced = collect_literals()
    problems = []

    for key, cand in sorted(CANDIDATES.items()):
        if key != cand.label:
            problems.append(
                f"registry key {key!r} != its entry's label "
                f"{cand.label!r} — verdicts store labels, a mismatch "
                f"makes them unroutable")
        fn = getattr(_spmv, cand.kernel, None)
        if not callable(fn):
            problems.append(
                f"candidate {cand.label!r} names kernel "
                f"{cand.kernel!r}, which is not a callable in "
                f"legate_sparse_tpu.ops.spmv — registry rotted")
        elif not traced.get(cand.kernel):
            problems.append(
                f"kernel {cand.kernel!r} has no 'trace.{cand.kernel}' "
                f"compile counter in the package — the jitted-kernel "
                f"instrumentation contract is broken")

    orphaned = sorted(l for l in CANDIDATES if not quoted.get(l))
    for label in orphaned:
        problems.append(
            f"candidate label {label!r} has NO quoted literal outside "
            f"the registry — no dispatch site serves it")

    try:
        with open(DOC_PATH) as f:
            doc = f.read()
    except OSError as e:
        doc = ""
        problems.append(f"docs/AUTOTUNER.md unreadable: {e}")
    undocumented = sorted(l for l in CANDIDATES if l not in doc)
    for label in undocumented:
        problems.append(
            f"candidate label {label!r} missing from docs/AUTOTUNER.md")

    if args.list:
        width = max(len(l) for l in CANDIDATES)
        for label in sorted(CANDIDATES):
            cand = CANDIDATES[label]
            where = ", ".join(sorted(set(quoted.get(label, [])))) or "-"
            print(f"{label.ljust(width)}  {cand.kernel}  "
                  f"ops={','.join(cand.ops)}  {where}")

    if problems:
        for p in problems:
            print(f"check_kernel_registry: {p}", file=sys.stderr)
        print(f"check_kernel_registry: FAILED "
              f"({len(problems)} problem(s))", file=sys.stderr)
        return 1
    if not args.list:
        print(f"check_kernel_registry: OK — {len(CANDIDATES)} "
              f"candidates, all wired and documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
