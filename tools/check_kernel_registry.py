#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Static kernel-registry coverage check (tier-1 via tests/test_autotune).

Thin back-compat wrapper: the analysis now lives in the sparselint
``kernel-registry`` rule (``tools/lint/rules/kernel_registry.py``; run
the whole suite with ``python tools/sparselint.py``).  This CLI keeps
the legacy entry point, flags, message wording and exit semantics.

The autotune candidate registry rots silently: a kernel rename in
``ops/spmv.py`` leaves ``autotune/registry.py`` advertising an entry
point that no longer exists, and a dropped dispatch label leaves
verdicts that can never be served.  The pass makes the three views of
the candidate list — the registry, the package's dispatch literals,
and the ``docs/AUTOTUNER.md`` candidate table — agree (plus the
structural invariant that each ``CANDIDATES`` key equals its entry's
label), and fails on any drift.

Usage::

    python tools/check_kernel_registry.py          # check, exit 0/1
    python tools/check_kernel_registry.py --list   # print the registry
"""

from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from legate_sparse_tpu.autotune.registry import CANDIDATES  # noqa: E402
from legate_sparse_tpu.ops import spmv as _spmv  # noqa: E402

from tools.lint.rules.kernel_registry import (  # noqa: E402
    collect_literals, problems_for)

__all__ = ["CANDIDATES", "collect_literals", "main"]

PKG_DIR = os.path.join(_REPO, "legate_sparse_tpu")
DOC_PATH = os.path.join(_REPO, "docs", "AUTOTUNER.md")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-check the autotune candidate registry "
                    "against ops/spmv.py kernels, dispatch-site "
                    "literals and docs.")
    ap.add_argument("--list", action="store_true",
                    help="print the registry with dispatch-site "
                         "locations")
    args = ap.parse_args(argv)

    # Read the module globals at call time (not via early-bound
    # defaults) so tests can monkeypatch CANDIDATES/PKG_DIR/DOC_PATH.
    pairs, quoted = problems_for(CANDIDATES, _spmv, PKG_DIR, DOC_PATH,
                                 _REPO)
    problems = [msg for msg, _rel in pairs]

    if args.list:
        width = max(len(l) for l in CANDIDATES)
        for label in sorted(CANDIDATES):
            cand = CANDIDATES[label]
            where = ", ".join(sorted(set(quoted.get(label, [])))) or "-"
            print(f"{label.ljust(width)}  {cand.kernel}  "
                  f"ops={','.join(cand.ops)}  {where}")

    if problems:
        for p in problems:
            print(f"check_kernel_registry: {p}", file=sys.stderr)
        print(f"check_kernel_registry: FAILED "
              f"({len(problems)} problem(s))", file=sys.stderr)
        return 1
    if not args.list:
        print(f"check_kernel_registry: OK — {len(CANDIDATES)} "
              f"candidates, all wired and documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
