#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Static obs-name coverage check (tier-1, mirroring
``check_fault_sites.py``).

The observability contract rots silently: a new ``obs.inc``/span/
histogram name ships, nobody adds it to the ``docs/OBSERVABILITY.md``
tables, and six PRs later the operator-facing reference describes half
the telemetry the package actually emits.  This pass extracts every
name literal passed to an obs emission entry point in
``legate_sparse_tpu/`` — counters (``inc``/``handle``), spans
(``span``/``complete_span``), events (``event``), and latency
histograms (``observe``/``handle``/``timer``) — and fails unless each
appears in docs/OBSERVABILITY.md, either verbatim or covered by a
documented prefix pattern (a backticked token ending in ``*`` or a
``<placeholder>`` segment, e.g. ``resil.*`` or ``mem.<phase>``).

f-strings contribute their literal prefix (``f"lat.spmv.{b}"`` →
``lat.spmv.``), which must be covered by a documented prefix; names
built entirely from variables are invisible to this pass (the same
limitation as check_fault_sites — keep at least a literal prefix at
emission sites).

Usage::

    python tools/check_obs_docs.py          # check, exit 0/1
    python tools/check_obs_docs.py --list   # dump extracted names
"""

from __future__ import annotations

import argparse
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

PKG_DIR = os.path.join(_REPO, "legate_sparse_tpu")
DOC_PATH = os.path.join(_REPO, "docs", "OBSERVABILITY.md")

# A quoted (optionally f-string) name as the first argument of an obs
# emission entry point.  The receiver alternatives cover the package's
# import aliases (obs / _obs / counters / _counters / trace / _trace /
# latency / _latency / _lat); the emission methods are the closed set
# of name-taking APIs.
EMIT_RE = re.compile(
    r"(?:\b(?:_?obs|_?counters|_?trace|_?latency|_lat)\.)"
    r"(?:inc|span|event|handle|observe|timer|complete_span)\(\s*\n?\s*"
    r"(f?)[\"']([^\"'\n]+)[\"']")

# Backticked tokens in the doc that look like emission names: dotted
# lowercase (counters/histograms/events) or bare span names.
DOC_TOKEN_RE = re.compile(r"`([A-Za-z0-9_.<>*/-]+)`")


def _py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def collect_emissions(root: str = PKG_DIR):
    """{name: [relpath, ...]} of emitted name literals; f-string names
    are reduced to their literal prefix and flagged: the value is
    ``(name_or_prefix, is_prefix)`` keys."""
    out = {}
    for path in _py_files(root):
        with open(path) as f:
            text = f.read()
        rel = os.path.relpath(path, _REPO)
        for fprefix, raw in EMIT_RE.findall(text):
            name = raw
            is_prefix = False
            if fprefix:
                cut = raw.find("{")
                if cut == 0:
                    continue    # no literal prefix: invisible here
                if cut > 0:
                    name = raw[:cut]
                    is_prefix = True
            # Concatenated-literal emissions ("lat.spmv." +
            # shape_bucket(...)) present as a trailing-dot literal —
            # treat like an f-string prefix.
            if name.endswith("."):
                is_prefix = True
            if not re.match(r"^[a-z][a-zA-Z0-9_.]*\.?$", name):
                continue        # not an emission name (messages etc.)
            out.setdefault((name, is_prefix), []).append(rel)
    return out


def doc_patterns(doc_text: str):
    """(exact_names, prefixes) from the doc's backticked tokens.  A
    token ending in ``*`` or containing a ``<placeholder>`` segment
    contributes its literal head as a prefix pattern."""
    exact = set()
    prefixes = set()
    for tok in DOC_TOKEN_RE.findall(doc_text):
        cut = len(tok)
        for ch in ("*", "<"):
            pos = tok.find(ch)
            if pos != -1:
                cut = min(cut, pos)
        if cut < len(tok):
            head = tok[:cut]
            if head:
                prefixes.add(head)
        else:
            exact.add(tok)
    return exact, prefixes


def documented(name: str, is_prefix: bool, exact, prefixes) -> bool:
    if not is_prefix and name in exact:
        return True
    for p in prefixes:
        if name.startswith(p):
            return True
    if is_prefix:
        # An f-string prefix is covered when some documented exact
        # name or pattern head extends it (the doc names the family).
        for t in exact:
            if t.startswith(name):
                return True
        for p in prefixes:
            if p.startswith(name):
                return True
    return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-check emitted obs names against the "
                    "docs/OBSERVABILITY.md tables.")
    ap.add_argument("--list", action="store_true",
                    help="print every extracted name with locations")
    args = ap.parse_args(argv)

    # Read the module globals at call time (not via early-bound
    # defaults) so tests can monkeypatch PKG_DIR/DOC_PATH.
    emissions = collect_emissions(PKG_DIR)
    try:
        with open(DOC_PATH) as f:
            doc = f.read()
    except OSError as e:
        print(f"check_obs_docs: docs/OBSERVABILITY.md unreadable: {e}",
              file=sys.stderr)
        return 1
    exact, prefixes = doc_patterns(doc)

    problems = []
    for (name, is_prefix), where in sorted(emissions.items()):
        if not documented(name, is_prefix, exact, prefixes):
            kind = "prefix" if is_prefix else "name"
            problems.append(
                f"emitted {kind} {name!r} (in "
                f"{', '.join(sorted(set(where)))}) is not covered by "
                f"any docs/OBSERVABILITY.md entry")

    if args.list:
        width = max(len(n) for (n, _p) in emissions) if emissions else 0
        for (name, is_prefix), where in sorted(emissions.items()):
            mark = "*" if is_prefix else " "
            print(f"{(name + mark).ljust(width + 1)}  "
                  f"{', '.join(sorted(set(where)))}")

    if problems:
        for p in problems:
            print(f"check_obs_docs: {p}", file=sys.stderr)
        print(f"check_obs_docs: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    if not args.list:
        print(f"check_obs_docs: OK — {len(emissions)} emission "
              f"literals, all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
