#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Static obs-name coverage check (tier-1, mirroring
``check_fault_sites.py``).

Thin back-compat wrapper: the analysis now lives in the sparselint
``obs-docs`` rule (``tools/lint/rules/obs_docs.py``; run the whole
suite with ``python tools/sparselint.py``).  This CLI keeps the legacy
entry point, flags, message wording and exit semantics.

The observability contract rots silently: a new ``obs.inc``/span/
histogram name ships, nobody adds it to the ``docs/OBSERVABILITY.md``
tables, and six PRs later the operator-facing reference describes half
the telemetry the package actually emits.  The pass extracts every
name literal passed to an obs emission entry point in
``legate_sparse_tpu/`` and fails unless each appears in
docs/OBSERVABILITY.md, verbatim or via a documented prefix pattern.
f-strings contribute their literal prefix; names built entirely from
variables are invisible (keep a literal prefix at emission sites).

Usage::

    python tools/check_obs_docs.py          # check, exit 0/1
    python tools/check_obs_docs.py --list   # dump extracted names
"""

from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from tools.lint.rules.obs_docs import (  # noqa: E402
    DOC_TOKEN_RE, EMIT_RE, collect_emissions, doc_patterns, documented,
    problems_for)

__all__ = ["EMIT_RE", "DOC_TOKEN_RE", "collect_emissions",
           "doc_patterns", "documented", "main"]

PKG_DIR = os.path.join(_REPO, "legate_sparse_tpu")
DOC_PATH = os.path.join(_REPO, "docs", "OBSERVABILITY.md")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-check emitted obs names against the "
                    "docs/OBSERVABILITY.md tables.")
    ap.add_argument("--list", action="store_true",
                    help="print every extracted name with locations")
    args = ap.parse_args(argv)

    # Read the module globals at call time (not via early-bound
    # defaults) so tests can monkeypatch PKG_DIR/DOC_PATH.
    pairs, emissions = problems_for(PKG_DIR, DOC_PATH, _REPO)
    problems = [msg for msg, _rel in pairs]

    if args.list:
        width = max(len(n) for (n, _p) in emissions) if emissions else 0
        for (name, is_prefix), where in sorted(emissions.items()):
            mark = "*" if is_prefix else " "
            print(f"{(name + mark).ljust(width + 1)}  "
                  f"{', '.join(sorted(set(where)))}")

    if problems:
        for p in problems:
            print(f"check_obs_docs: {p}", file=sys.stderr)
        print(f"check_obs_docs: FAILED ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    if not args.list:
        print(f"check_obs_docs: OK — {len(emissions)} emission "
              f"literals, all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
