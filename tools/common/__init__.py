# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Shared static-analysis plumbing for the repo's gate tools.

``sparselint`` (``tools/lint/``, AST-level source invariants) and
``planverify`` (``tools/verify/``, lowered-program contracts) present
the same operator surface — ``path:line: severity: [rule-id] message``
findings, a committed line-number-free baseline with stale-entry
detection, deterministic 0/1/2 exit codes, a ``--json`` artifact — so
the finding/baseline core lives here once and both frameworks import
it.  Anything rule-model-specific (AST contexts, inline suppressions,
lowering catalogs) stays in the owning tool.
"""

from .findings import (  # noqa: F401
    Finding, load_baseline, write_baseline,
)
