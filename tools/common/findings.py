# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Findings and baselines, shared by sparselint and planverify.

- A **finding** is one rule violation at a source location (or, for
  planverify, at a lowered program — ``path`` then names the program's
  defining module and ``message`` carries the program id).
- The **baseline** grandfathers findings in a committed JSON file
  keyed ``(rule, path, message)`` — deliberately line-number-free so
  unrelated edits above a grandfathered site don't resurrect it.
  Entries are a multiset (two identical findings need two entries);
  entries that match nothing are reported by the runners as *stale*
  so the baseline shrinks instead of rotting.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str           # repo-relative, "/"-separated
    line: int           # 1-based; 0 = whole-file/whole-program
    message: str
    severity: str = "error"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline entries as a multiset of (rule, path, message)."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("entries", []):
        key = (e["rule"], e["path"], e["message"])
        out[key] = out.get(key, 0) + 1
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = sorted(
        ({"rule": f.rule, "path": f.path, "message": f.message}
         for f in findings),
        key=lambda e: (e["rule"], e["path"], e["message"]))
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
