#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Automated performance doctor: offline diagnosis over obs artifacts.

``trace_summary.py`` renders ledgers; the doctor *reads* them.  Point
it at any mix of the four artifact kinds the package emits —

- Chrome-trace JSON (``bench.py`` / ``obs.write_chrome_trace``; the
  ``otherData`` blob carries counters, histograms and the bench
  result),
- OpenMetrics text (``LEGATE_SPARSE_TPU_OBS_PROM`` snapshots,
  ``obs.export.write_openmetrics``),
- bench result JSON (``bench.py`` output, driver wrappers, log tails),
- planverify JSON (``python tools/planverify.py --json``; detected by
  its ``"tool": "planverify"`` key)

— and it cross-references them into a ranked findings table: breaker
trips, plan-cache thrash, batch occupancy collapse, comm-bytes
actual-vs-predicted drift, compiled-plan contract drift, CPU roofline
shortfall (with the measured loss terms ranked), gateway rejection
pressure, SLO budget burns, and observability overhead.  Every finding carries a remediation hint —
the docs section or knob to reach for next.

Artifact kind is auto-detected from content, never from the filename.

Usage::

    python tools/doctor.py BENCH_x.json run.trace.json metrics.prom
    python tools/doctor.py --check evidence/BENCH_golden_smoke.json
    python tools/doctor.py --check --fail-on warn artifacts/*.json

``--check`` makes the exit status a CI verdict: 1 when any finding at
or above ``--fail-on`` severity (default ``critical``) is present,
0 otherwise, 2 when no artifact could be read.  Without ``--check``
the exit status is always 0 (report, don't judge).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

from legate_sparse_tpu.obs import export, regress, report  # noqa: E402

SEVERITIES = ("info", "warn", "critical")

# Thresholds (module constants so tests can reference them).
PLAN_HIT_RATE_FLOOR = 0.5
BATCH_OCCUPANCY_FLOOR = 2.0
COMM_DELTA_TOL = 0.01
ROOFLINE_FLOOR = 0.7
GATEWAY_REJECT_CEIL = 0.10
OBS_OVERHEAD_CEIL_PCT = 5.0
# A tenant holding more than this share of attributed device time is
# a noisy-neighbor candidate; the finding fires only while some SLO
# burns at page level (the obs/slo.py fast-window breach threshold).
NOISY_NEIGHBOR_SHARE = 0.5
SLO_PAGE_BURN = 14.4
# Same-shape-bucket COO->CSR rebuild count at which a workload looks
# like streaming mutation being served by full reconstruction — the
# delta-disabled-but-rebuilding evidence (docs/MUTATION.md).
COO_REBUILD_FLOOR = 3


def _severity_rank(sev: str) -> int:
    return SEVERITIES.index(sev)


class Evidence:
    """Merged view over every artifact read: counters (summed across
    artifacts — each is a monotone ledger of its own process), the
    latest histograms, the latest bench result, and all trace
    records."""

    def __init__(self) -> None:
        self.counters: Dict[str, float] = {}
        self.histograms: Dict[str, Any] = {}
        self.bench: Dict[str, Any] = {}
        self.records: List[Dict[str, Any]] = []
        self.verify_findings: List[Dict[str, Any]] = []
        self.verify_stale: List[Dict[str, Any]] = []
        self.verify_programs: List[str] = []
        self.sources: List[str] = []

    def add_counters(self, counters: Dict[str, Any]) -> None:
        for name, val in counters.items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                self.counters[name] = self.counters.get(name, 0) + val

    def counter(self, name: str, default: float = 0.0) -> float:
        return self.counters.get(name, default)

    def field(self, name: str, default=None):
        """Bench-result field lookup."""
        val = self.bench.get(name, default)
        return default if val is None else val


def load_artifact(path: str, ev: Evidence) -> str:
    """Read one artifact into the evidence, returning the detected
    kind (``openmetrics`` / ``trace`` / ``planverify`` / ``bench``).
    Raises ValueError
    when the content matches none of them."""
    with open(path) as f:
        text = f.read()
    stripped = text.strip()
    if stripped.startswith(f"# TYPE {export._PREFIX}") or \
            f"{export._PREFIX}_counter_total" in stripped.split("\n", 3)[0]:
        counters, hists = export.parse_openmetrics(text)
        ev.add_counters(counters)
        ev.histograms.update(hists)
        ev.sources.append(f"{path} (openmetrics)")
        return "openmetrics"
    try:
        doc = json.loads(stripped)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "traceEvents" in doc:
        ev.records.extend(report.load_records(path))
        meta = doc.get("otherData") or {}
        ev.add_counters(meta.get("counters") or {})
        ev.histograms.update(meta.get("histograms") or {})
        bench = meta.get("bench_result")
        if isinstance(bench, dict):
            ev.bench.update(bench)
        ev.sources.append(f"{path} (trace)")
        return "trace"
    if isinstance(doc, dict) and doc.get("tool") == "planverify":
        ev.verify_findings.extend(doc.get("findings") or [])
        ev.verify_stale.extend(doc.get("stale_baseline") or [])
        ev.verify_programs.extend(doc.get("programs_checked") or [])
        ev.sources.append(f"{path} (planverify)")
        return "planverify"
    bench = regress.load_bench(path)      # raises ValueError if not one
    ev.bench.update(bench)
    ev.sources.append(f"{path} (bench)")
    return "bench"


def _finding(sev: str, code: str, message: str, hint: str,
             value: Optional[str] = None) -> Dict[str, str]:
    return {"severity": sev, "code": code, "message": message,
            "hint": hint, "value": value or "-"}


def _parse_verdict_key(key_id: str):
    """Split a ``VerdictKey.key_id`` —
    ``op/dtype/fp_class/rN/zN/kN[/sTAG]@platform/eN`` — into
    ``(op, dtype, fp_class, shape-bucket tuple)``, or None when the id
    doesn't parse.  The optional ``/s`` storage tag and the
    platform/epoch suffix are deliberately excluded: the
    storage-wider-than-verdict rule compares *structural* identity
    across storage representations."""
    head = key_id.split("@", 1)[0]
    parts = head.split("/")
    if parts and parts[-1][:1] == "s":
        parts = parts[:-1]
    if len(parts) < 6:
        return None
    shape = tuple(parts[-3:])
    if [t[:1] for t in shape] != ["r", "z", "k"]:
        return None
    return parts[0], parts[1], "/".join(parts[2:-3]), shape


def diagnose(ev: Evidence) -> List[Dict[str, str]]:
    """Run every rule over the merged evidence; findings ranked
    critical-first, stable within severity (rule order)."""
    out: List[Dict[str, str]] = []

    # -- SLO budget burns: the one signal that is a page, not a smell.
    breaches = {name[len("slo.breach."):]: val
                for name, val in ev.counters.items()
                if name.startswith("slo.breach.") and val}
    for slo_name in sorted(breaches):
        out.append(_finding(
            "critical", "slo-breach",
            f"SLO '{slo_name}' burned its error budget "
            f"{int(breaches[slo_name])}x (fast-window burn >= page "
            f"threshold)",
            "docs/OBSERVABILITY.md 'SLO registry': inspect "
            "trace_summary --slo, then the lat.* histograms behind "
            "the objective",
            str(int(breaches[slo_name]))))

    # -- Noisy neighbor: one tenant monopolizes the attributed device
    #    time (obs/attrib.py ledger) while some SLO burns at page
    #    level — the capacity signal the submesh-carving actuator
    #    (ROADMAP item 2) exists for.
    wall: Dict[str, float] = {}
    for name, val in ev.counters.items():
        if (name.startswith("attrib.tenant.")
                and name.endswith(".wall_ns")):
            tenant = name[len("attrib.tenant."):-len(".wall_ns")]
            if tenant not in ("__untagged__", "__other__"):
                wall[tenant] = wall.get(tenant, 0) + val
    total_wall = sum(wall.values())
    burning = bool(breaches)
    if not burning:
        for rec in ev.records:
            if (rec.get("type") == "event"
                    and rec.get("name") == "slo.verdict"
                    and float((rec.get("attrs") or {})
                              .get("fast_burn", 0.0)) >= SLO_PAGE_BURN):
                burning = True
                break
    if total_wall > 0 and len(wall) >= 2 and burning:
        hog, hog_ns = max(wall.items(), key=lambda kv: (kv[1], kv[0]))
        share = hog_ns / total_wall
        if share > NOISY_NEIGHBOR_SHARE:
            out.append(_finding(
                "warn", "noisy-neighbor",
                f"tenant '{hog}' holds {share:.0%} of attributed "
                f"device time while an SLO burns at page level",
                "docs/OBSERVABILITY.md 'Per-tenant attribution': "
                "carve the hog a dedicated submesh (reshard(), "
                "ROADMAP item 2) or tighten its gateway rate/quota "
                "knobs",
                f"{share:.2f}"))
            # The actuator for exactly this smell exists
            # (legate_sparse_tpu/placement); if no placement.* counter
            # moved in the evidence, the control loop that would carve
            # the hog its own submesh never ran.
            if not any(n.startswith("placement.")
                       for n in ev.counters):
                out.append(_finding(
                    "info", "placement-disabled-while-noisy-neighbor",
                    "a noisy-neighbor burns an SLO but the elastic "
                    "placement controller is off (no placement.* "
                    "counters in the evidence)",
                    "set LEGATE_SPARSE_TPU_PLACEMENT=1 and drive "
                    "PlacementController.step() (docs/PLACEMENT.md) "
                    "so the hog is carved a dedicated submesh "
                    "automatically",
                    "-"))

    # -- Migration thrash: the placement controller re-migrated a
    #    tenant within its own cooldown without the tenant's burn
    #    improving — the control loop is oscillating, not converging.
    thrash = ev.counter("placement.thrash")
    if thrash:
        out.append(_finding(
            "warn", "migration-thrash",
            f"placement controller re-migrated a still-burning tenant "
            f"within cooldown {int(thrash)}x (oscillating, not "
            f"converging)",
            "raise LEGATE_SPARSE_TPU_PLACEMENT_COOLDOWN_MS or "
            "LEGATE_SPARSE_TPU_PLACEMENT_AMORTIZE so migrations must "
            "pay for themselves; inspect trace_summary --placement",
            str(int(thrash))))

    # -- Compaction lagging: the delta side-buffer crossed its
    #    watermark while some SLO burns at page level — every serve
    #    pays the two-term dispatch on a near-full buffer instead of
    #    the merged base, and mutation pressure is outrunning the
    #    background merge.
    wm = ev.counter("delta.watermark.exceeded")
    if wm and burning:
        out.append(_finding(
            "warn", "compaction-lagging",
            f"delta buffer crossed its compaction watermark "
            f"{int(wm)}x while an SLO burns at page level (mutation "
            f"pressure outrunning the background merge)",
            "lower LEGATE_SPARSE_TPU_DELTA_WATERMARK (compact "
            "earlier) or arm/shorten LEGATE_SPARSE_TPU_DELTA_"
            "WORKER_MS (docs/MUTATION.md); inspect trace_summary "
            "--delta",
            str(int(wm))))

    # -- Rebuilding what the delta layer would serve: repeated
    #    same-shape-bucket COO->CSR constructions with the delta flag
    #    off — the workload is mutating by full reconstruction, the
    #    exact cost the side-buffer + background-compaction path
    #    amortizes away.
    if not any(n.startswith("delta.") for n in ev.counters):
        rebuilds = {name[len("build.csr.coo."):]: val
                    for name, val in ev.counters.items()
                    if name.startswith("build.csr.coo.")
                    and val >= COO_REBUILD_FLOOR}
        if rebuilds:
            bucket, n = max(rebuilds.items(),
                            key=lambda kv: (kv[1], kv[0]))
            out.append(_finding(
                "info", "delta-disabled-but-rebuilding",
                f"{int(n)} same-shape COO->CSR rebuilds (bucket "
                f"{bucket}) with the delta layer off — mutation "
                f"served by full reconstruction",
                "set LEGATE_SPARSE_TPU_DELTA=1 and serve updates "
                "through DeltaCSR.update() + background compaction "
                "(docs/MUTATION.md) instead of rebuilding",
                str(int(n))))

    # -- Compiled-plan contract drift: the lowered IR no longer
    #    matches the committed planverify contract.  Critical, not a
    #    smell: either a dist kernel silently changed its collective
    #    pattern/byte volume, or an intended change shipped without
    #    regenerating its contract.
    for vf in ev.verify_findings:
        out.append(_finding(
            "critical", "plan-contract-drift",
            f"planverify [{vf.get('rule', '?')}] {vf.get('path', '?')}"
            f": {vf.get('message', '')}",
            "re-run `python tools/planverify.py` after reverting the "
            "drift; if the new lowering is intended, regenerate via "
            "`--update-contracts --reason '...'` (docs/VERIFY.md)",
            vf.get("rule", "-")))
    for entry in ev.verify_stale:
        out.append(_finding(
            "info", "verify-stale-baseline",
            f"planverify baseline entry [{entry.get('rule', '?')}] "
            f"{entry.get('path', '?')} matches no current finding",
            "delete the stale entry from tools/verify/baseline.json "
            "so the grandfather list shrinks instead of rotting",
            entry.get("rule", "-")))

    # -- Breaker trips: capacity was protected by failing fast.
    trips = ev.counter("resil.breaker.trips") or ev.field(
        "resil_breaker_trips", 0)
    if trips:
        out.append(_finding(
            "warn", "breaker-trips",
            f"circuit breaker tripped {int(trips)}x — downstream "
            f"failures crossed the trip threshold",
            "docs/RESILIENCE.md: check resil.breaker.*.trips sites "
            "via trace_summary --resil; raise capacity or fix the "
            "failing dependency before tuning thresholds",
            str(int(trips))))

    # -- Recovery without checkpoint advance: device losses were
    #    survived, but every recovery restarted from scratch (or from
    #    one stale snapshot) — the checkpoint cadence is off or far
    #    coarser than the loss rate, so restored work is being lost.
    recoveries = ev.counter("resil.recovery.attempts") or ev.field(
        "resil_recoveries", 0)
    ck_saves = ev.counter("resil.ckpt.saves") or ev.field(
        "resil_ckpt_saves", 0)
    if recoveries and not ck_saves:
        out.append(_finding(
            "warn", "recovery-without-checkpoint-advance",
            f"{int(recoveries)} device-loss recoveries ran with zero "
            f"checkpoint saves — every recovery restarted from x0",
            "docs/RESILIENCE.md: set LEGATE_SPARSE_TPU_RESIL_CKPT_"
            "ITERS (or open checkpoint.scope) so restores resume "
            "from a recent iterate instead of replaying the solve",
            str(int(recoveries))))

    # -- Plan-cache thrash: every miss is an XLA recompile.
    hits = ev.counter("engine.plan.hits") or ev.field(
        "engine_plan_hits", 0)
    misses = ev.counter("engine.plan.misses") or ev.field(
        "engine_plan_misses", 0)
    if hits + misses:
        rate = hits / (hits + misses)
        if rate < PLAN_HIT_RATE_FLOOR:
            out.append(_finding(
                "warn", "plan-thrash",
                f"engine plan-cache hit rate {rate:.0%} (< "
                f"{PLAN_HIT_RATE_FLOOR:.0%}) — shape churn is forcing "
                f"recompiles",
                "docs/ENGINE.md: widen pad buckets "
                "(LEGATE_SPARSE_TPU_ENGINE knobs) or raise the plan "
                "cache capacity",
                f"{rate:.2f}"))

    # -- Autotune decline ladder: measurements that never pay off.
    at_declines = ev.counter("autotune.route.declined")
    at_hits = ev.counter("autotune.route.hit")
    if at_declines and at_declines > at_hits:
        out.append(_finding(
            "warn", "autotune-declines",
            f"autotuner declined routing {int(at_declines)}x vs "
            f"{int(at_hits)} routed hits — measured verdicts are not "
            f"being reused",
            "docs/AUTOTUNER.md: check the decline ladder "
            "(autotune.route.* counters); stale store? "
            "LEGATE_SPARSE_TPU_AUTOTUNE_STORE path writable?",
            str(int(at_declines))))

    # -- Storage wider than verdict: the autotuner measured a
    #    bf16-storage winner for a fingerprint class, yet f32 storage
    #    of the same class is still being tuned/dispatched — the
    #    compressed-storage byte win is sitting idle.
    bf16_classes: Dict[tuple, str] = {}
    f32_classes: Dict[tuple, str] = {}
    for rec in ev.records:
        if rec.get("name") != "autotune.verdict":
            continue
        attrs = rec.get("attrs") or {}
        parsed = _parse_verdict_key(str(attrs.get("key", "")))
        if parsed is None:
            continue
        op, dtype, klass, shape = parsed
        if dtype in ("bfloat16", "float16"):
            bf16_classes[(op, klass, shape)] = str(
                attrs.get("label", "?"))
        elif dtype == "float32":
            f32_classes[(op, klass, shape)] = str(attrs.get("key"))
    for group in sorted(set(bf16_classes) & set(f32_classes)):
        op, klass, shape = group
        out.append(_finding(
            "warn", "storage-wider-than-verdict",
            f"f32 storage is being dispatched for {op}/{klass}/"
            f"{'/'.join(shape)} although a compressed-storage verdict "
            f"({bf16_classes[group]!r}) exists for the same "
            f"fingerprint class — the measured byte win is sitting "
            f"idle",
            "csr_array.compress() the operand (bf16 values + int16 "
            "indices) so the *-bf16 verdict serves the dispatch "
            "(docs/AUTOTUNER.md 'Candidates'); keep f32 storage only "
            "where the rounding is unacceptable",
            f32_classes[group]))

    # -- Batch occupancy: a batching engine running solo requests.
    for label, breq, batches in (
            ("executor", ev.counter("engine.exec.batched_requests"),
             ev.counter("engine.exec.batches")),
            ("gateway", ev.counter("gateway.dispatched_requests"),
             ev.counter("gateway.dispatches"))):
        if batches >= 4 and breq / batches < BATCH_OCCUPANCY_FLOOR:
            out.append(_finding(
                "info", "batch-occupancy",
                f"{label} batch occupancy {breq / batches:.1f} "
                f"reqs/batch over {int(batches)} batches (< "
                f"{BATCH_OCCUPANCY_FLOOR:.0f}) — batching overhead "
                f"without batching wins",
                "docs/ENGINE.md: raise the batch window "
                "(_ENGINE_WINDOW_US) or submit concurrently; solo "
                "streams may prefer inline dispatch",
                f"{breq / batches:.1f}"))

    # -- Comm bytes, counted vs bench-recorded: drift means the
    #    predictive model and the dist kernels disagree.
    counted = ev.counter("comm.total_bytes")
    recorded = ev.field("comm_total_bytes")
    if counted and isinstance(recorded, (int, float)) and recorded:
        delta = abs(counted - recorded) / recorded
        if delta > COMM_DELTA_TOL:
            out.append(_finding(
                "warn", "comm-drift",
                f"comm.total_bytes counter ({int(counted)}) vs bench "
                f"comm_total_bytes ({int(recorded)}) differ "
                f"{delta:.1%} (> {COMM_DELTA_TOL:.0%})",
                "docs/DIST.md accounting contract: a dist kernel "
                "changed its collective pattern without updating "
                "obs/comm.py predictions (or vice versa)",
                f"{delta:.3f}"))

    # -- CPU roofline shortfall, with the measured loss terms ranked.
    ratio = ev.field("cpu_roofline_ratio")
    if isinstance(ratio, (int, float)) and ratio < ROOFLINE_FLOOR:
        items = ev.field("cpu_roofline_items") or {}
        ranked = sorted(
            ((k, v) for k, v in items.items()
             if isinstance(v, (int, float))),
            key=lambda kv: -kv[1])
        detail = ", ".join(f"{k}={v:.2f}" for k, v in ranked[:3])
        out.append(_finding(
            "warn", "roofline-shortfall",
            f"cpu_roofline_ratio {ratio:.2f} (< {ROOFLINE_FLOOR}) — "
            f"SpMV is leaving measured bandwidth on the table"
            + (f"; top losses: {detail}" if detail else ""),
            "bench.py itemizes the loss terms "
            "(cpu_roofline_items); attack the largest first "
            "(mask/pad losses -> layout, segment-sum -> kernel)",
            f"{ratio:.2f}"))

    # -- Gateway rejection pressure.
    submitted = ev.counter("gateway.submitted") or ev.field(
        "gateway_requests", 0)
    rejected = sum(v for name, v in ev.counters.items()
                   if name.startswith("gateway.rejected."))
    if not rejected:
        rejected = sum(
            v for k, v in ev.bench.items()
            if k.startswith("gateway_rejected_")
            and isinstance(v, (int, float)))
    if submitted and rejected / submitted > GATEWAY_REJECT_CEIL:
        out.append(_finding(
            "warn", "gateway-rejections",
            f"gateway rejected {int(rejected)}/{int(submitted)} "
            f"submissions ({rejected / submitted:.0%} > "
            f"{GATEWAY_REJECT_CEIL:.0%}) — admission pressure exceeds "
            f"capacity",
            "docs/OBSERVABILITY.md gateway ledger: split by reason "
            "(trace_summary --gateway); queue_full -> raise "
            "queue/quota knobs, breaker -> see breaker-trips",
            f"{rejected / submitted:.2f}"))

    # -- Observability overhead.  Smoke-lane artifacts are excluded:
    #    the CI toy matrix runs SpMV in microseconds, so the relative
    #    span tax there is dominated by the probe itself and would
    #    flap the otherwise-deterministic finding set.
    overhead = ev.field("obs_overhead_pct")
    if isinstance(overhead, (int, float)) and \
            not ev.field("smoke", False) and \
            overhead > OBS_OVERHEAD_CEIL_PCT:
        out.append(_finding(
            "warn", "obs-overhead",
            f"obs_overhead_pct {overhead:.1f}% (> "
            f"{OBS_OVERHEAD_CEIL_PCT:.0f}%) — tracing is taxing the "
            f"hot path",
            "run with LEGATE_SPARSE_TPU_OBS unset in production; "
            "spans are the only toggled cost (counters/histograms "
            "are always-on by design)",
            f"{overhead:.1f}"))

    # -- Dropped records: the trace itself is lying by omission.
    dropped = ev.counter("obs.dropped_records")
    if dropped:
        out.append(_finding(
            "info", "trace-dropped",
            f"{int(dropped)} trace records dropped at the MAX_RECORDS "
            f"cap — per-op tables undercount",
            "docs/OBSERVABILITY.md: reset/export the trace "
            "periodically, or trace a shorter window",
            str(int(dropped))))

    out.sort(key=lambda f: -_severity_rank(f["severity"]))
    return out


def render_findings(findings: List[Dict[str, str]],
                    verbose_hints: bool = True) -> str:
    if not findings:
        return "doctor: no findings — all ledgers within thresholds"
    rows = [[f["severity"].upper(), f["code"], f["value"], f["message"]]
            for f in findings]
    out = [report.format_table(
        ["severity", "finding", "value", "detail"], rows, left_cols=4)]
    if verbose_hints:
        out.append("")
        for f in findings:
            out.append(f"[{f['code']}] hint: {f['hint']}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Ranked diagnosis over obs artifacts (Chrome "
                    "trace / OpenMetrics / bench JSON).")
    ap.add_argument("artifacts", nargs="+",
                    help="artifact files; kind auto-detected from "
                         "content")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: exit 1 when any finding reaches "
                         "--fail-on severity")
    ap.add_argument("--fail-on", choices=SEVERITIES, default="critical",
                    help="minimum severity that fails --check "
                         "(default: critical)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array instead of "
                         "the table")
    ap.add_argument("--no-hints", action="store_true",
                    help="omit the remediation-hint lines")
    args = ap.parse_args(argv)

    ev = Evidence()
    for path in args.artifacts:
        try:
            kind = load_artifact(path, ev)
        except (OSError, ValueError) as exc:
            print(f"doctor: cannot read {path}: {exc}", file=sys.stderr)
            continue
        print(f"doctor: read {path} ({kind})", file=sys.stderr)
    if not ev.sources:
        print("doctor: no readable artifacts", file=sys.stderr)
        return 2

    findings = diagnose(ev)
    if args.json:
        print(json.dumps(findings, indent=2))
    else:
        print(render_findings(findings,
                              verbose_hints=not args.no_hints))

    if args.check:
        floor = _severity_rank(args.fail_on)
        if any(_severity_rank(f["severity"]) >= floor
               for f in findings):
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
