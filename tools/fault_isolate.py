# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Isolate the on-chip "TPU worker crashed" fault seen in bench.py.

Runs one configuration per SUBPROCESS (a worker crash kills only that
probe), most-diagnostic-first, and appends each verdict to
TPU_EVIDENCE.md the moment it lands.  Configurations walk the exact
bench path (diags -> csr -> SpMV dispatch; the bench band is exact, so
the kernel runs unmasked) across sizes x {pallas, xla}, and each
probe reports eager launches AND the chained-fori_loop composition
separately — the pack-time eager validation passed on-chip while
bench's looped timing crashed the worker, so the composition is a
prime suspect.

Usage: python tools/fault_isolate.py [--quick]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_EVIDENCE.md")

PROBE = r"""
import json, os, sys, time
import numpy as np
log2 = int(sys.argv[1])
mode = sys.argv[2]            # pallas | xla
if mode == "xla":
    os.environ["LEGATE_SPARSE_TPU_PALLAS_DIA"] = "0"
import jax
import jax.numpy as jnp
import legate_sparse_tpu as sparse

n = 1 << log2
nnz_per_row = 11
offsets = list(range(-(nnz_per_row // 2), nnz_per_row // 2 + 1))
diagonals = [np.full(n - abs(o), 1.0 + o * 0.01, dtype=np.float32)
             for o in offsets]
t0 = time.time()
A = sparse.diags(diagonals, offsets, shape=(n, n), format="csr",
                 dtype=np.float32)
x = jnp.ones((n,), dtype=jnp.float32)
build_s = time.time() - t0
path = ("dia" if A._get_dia() is not None else "csr")
pk = A._get_dia_pack() if mode == "pallas" else None
out = {"log2": log2, "mode": mode, "path": path,
       "packed": pk is not None, "build_s": round(build_s, 1)}
expect = float(np.sum([d.sum() for d in diagonals]))

# Stage 1: eager launches (one pallas_call per dispatch).
t0 = time.time()
y = A @ x
s1 = float(jnp.sum(y))          # forces fetch through the tunnel
out["eager_first_s"] = round(time.time() - t0, 1)
t0 = time.time()
for _ in range(3):
    y = A @ x
float(jnp.sum(y))
out["eager_rep_s"] = round((time.time() - t0) / 3, 3)
out["eager_correct"] = abs(s1 - expect) < 1e-2 * max(1.0, abs(expect))
print(json.dumps(out), flush=True)   # partial verdict survives a crash

# Stage 2: the chained fori_loop composition bench.py times (the
# pallas_call embedded in a larger jitted looped program) — this is
# the stage bench crashed in while eager pack-validation passed.
from legate_sparse_tpu.bench_timing import loop_ms_per_iter
dt_ms = loop_ms_per_iter(lambda v: A @ v, x, k_lo=2, k_hi=6)
out["loop_ms_per_iter"] = round(dt_ms, 3)
y2 = A @ x
out["loop_correct"] = (abs(float(jnp.sum(y2)) - expect)
                       < 1e-2 * max(1.0, abs(expect)))
print(json.dumps(out), flush=True)
"""


def append(text: str) -> None:
    with open(OUT, "a") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())


def run(log2: int, mode: str, timeout_s: int = 420) -> dict:
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE, str(log2), mode],
            capture_output=True, text=True, timeout=timeout_s,
        )
        wall = round(time.time() - t0, 1)
        line = (r.stdout or "").strip().splitlines()
        parsed = None
        for ln in reversed(line):
            try:
                parsed = json.loads(ln)
                break
            except Exception:
                continue
        if r.returncode == 0 and parsed:
            parsed["wall_s"] = wall
            return parsed
        return {"log2": log2, "mode": mode, "rc": r.returncode,
                "wall_s": wall,
                "stderr": (r.stderr or "")[-400:].strip()}
    except subprocess.TimeoutExpired as e:
        return {"log2": log2, "mode": mode, "rc": "timeout",
                "wall_s": timeout_s,
                "stderr": ((e.stderr or b"").decode("utf-8", "replace")
                           if isinstance(e.stderr, bytes)
                           else (e.stderr or ""))[-400:].strip()}


def main() -> None:
    quick = "--quick" in sys.argv
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S")
    append(f"\n## Fault isolation {stamp}\n\n"
           "One subprocess per row (bench's exact diags->SpMV path); a "
           "crash poisons only its own row.\n\n```json\n")
    sizes = [16, 20, 22, 24] if not quick else [16, 22]
    for log2 in sizes:
        for mode in ("pallas", "xla"):
            # big sizes pay multi-minute tunnel uploads before compute
            res = run(log2, mode, timeout_s=420 if log2 < 22 else 700)
            append(json.dumps(res) + "\n")
            print(json.dumps(res), flush=True)
            bad = res.get("rc") not in (None,) or not res.get("correct", True)
            if mode == "pallas" and bad and str(res.get("rc")) == "timeout":
                # worker likely wedged; give it one recovery pause
                time.sleep(60)
    append("```\n")


if __name__ == "__main__":
    main()
