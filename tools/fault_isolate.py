# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Isolate the on-chip "TPU worker crashed" fault seen in bench.py.

Runs one configuration per SUBPROCESS (a worker crash kills only that
probe), most-diagnostic-first, and appends each verdict to
TPU_EVIDENCE.md the moment it lands.  Configurations walk the exact
bench path (diags -> csr -> SpMV dispatch; the bench band is exact, so
the kernel runs unmasked) across sizes x {pallas, xla}, and each
probe reports eager launches AND the chained-fori_loop composition
separately — the pack-time eager validation passed on-chip while
bench's looped timing crashed the worker, so the composition is a
prime suspect.

Usage: python tools/fault_isolate.py [--quick]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_EVIDENCE.md")

PROBE = r"""
import json, os, sys, time
import numpy as np
log2 = int(sys.argv[1])
mode = sys.argv[2]    # pallas | pallas-shift3 | pallas-jroll | xla
if mode == "xla":
    os.environ["LEGATE_SPARSE_TPU_PALLAS_DIA"] = "0"
elif mode == "pallas-jroll":
    os.environ["LEGATE_SPARSE_TPU_PALLAS_ROLL"] = "xla"
elif mode == "pallas-shift3":
    os.environ["LEGATE_SPARSE_TPU_PALLAS_INPUTS"] = "distinct"
import jax
import jax.numpy as jnp
import legate_sparse_tpu as sparse

n = 1 << log2
nnz_per_row = 11
offsets = list(range(-(nnz_per_row // 2), nnz_per_row // 2 + 1))
diagonals = [np.full(n - abs(o), 1.0 + o * 0.01, dtype=np.float32)
             for o in offsets]
t0 = time.time()
A = sparse.diags(diagonals, offsets, shape=(n, n), format="csr",
                 dtype=np.float32)
x = jnp.ones((n,), dtype=jnp.float32)
build_s = time.time() - t0
path = ("dia" if A._get_dia() is not None else "csr")
pk = A._get_dia_pack() if mode.startswith("pallas") else None
out = {"log2": log2, "mode": mode, "path": path,
       "packed": pk is not None, "build_s": round(build_s, 1)}
expect = float(np.sum([d.sum() for d in diagonals]))

# Stage 1: eager launches (one pallas_call per dispatch).
t0 = time.time()
y = A @ x
s1 = float(jnp.sum(y))          # forces fetch through the tunnel
out["eager_first_s"] = round(time.time() - t0, 1)
t0 = time.time()
for _ in range(3):
    y = A @ x
float(jnp.sum(y))
out["eager_rep_s"] = round((time.time() - t0) / 3, 3)
out["eager_correct"] = abs(s1 - expect) < 1e-2 * max(1.0, abs(expect))
print(json.dumps(out), flush=True)   # partial verdict survives a crash

# Stage 2: the chained fori_loop composition bench.py times (the
# pallas_call embedded in a larger jitted looped program) — this is
# the stage bench crashed in while eager pack-validation passed.
from legate_sparse_tpu.bench_timing import loop_ms_per_iter
try:
    dt_ms = loop_ms_per_iter(lambda v: A @ v, x, k_lo=2, k_hi=6, k_cap=24)
    out["loop_ms_per_iter"] = round(dt_ms, 3)
except RuntimeError as e:
    # Unresolvable timing under the capped trip count: the looped
    # programs still RAN (survival is this probe's verdict); record
    # the resolution failure without poisoning the row with an rc.
    out["loop_ms_per_iter"] = None
    out["loop_timing_note"] = repr(e)[:120]
y2 = A @ x
out["loop_correct"] = (abs(float(jnp.sum(y2)) - expect)
                       < 1e-2 * max(1.0, abs(expect)))
print(json.dumps(out), flush=True)
"""


def append(text: str) -> None:
    with open(OUT, "a") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())


def _last_json(text: str):
    for ln in reversed((text or "").strip().splitlines()):
        try:
            return json.loads(ln)
        except Exception:
            continue
    return None


def run(log2: int, mode: str, timeout_s: int = 420) -> dict:
    """One probe subprocess.  The stage-1 (eager) partial verdict is
    KEPT on crash/timeout — the 'eager ok, loop crashed' distinction is
    the whole point of the two-stage probe."""
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE, str(log2), mode],
            capture_output=True, text=True, timeout=timeout_s,
        )
        out = _last_json(r.stdout) or {"log2": log2, "mode": mode}
        out["wall_s"] = round(time.time() - t0, 1)
        if r.returncode != 0:
            out["rc"] = r.returncode
            out["stderr"] = (r.stderr or "")[-400:].strip()
        return out
    except subprocess.TimeoutExpired as e:
        def _txt(b):
            return (b.decode("utf-8", "replace")
                    if isinstance(b, bytes) else (b or ""))
        out = _last_json(_txt(e.stdout)) or {"log2": log2, "mode": mode}
        out["rc"] = "timeout"
        out["wall_s"] = timeout_s
        out["stderr"] = _txt(e.stderr)[-400:].strip()
        return out


def main() -> None:
    quick = "--quick" in sys.argv
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S")
    append(f"\n## Fault isolation {stamp}\n\n"
           "One subprocess per row (bench's exact diags->SpMV path); a "
           "crash poisons only its own row.\n\n```json\n")
    # Per-probe budgets (+ recovery pauses BETWEEN probes) must SUM
    # below the capture script's outer timeout (quick: 2*390+45 < 900;
    # full: 4440 + pauses < 5400) so the closing fence and later
    # phases always run.  Quick mode exists to NAME the crashing
    # configuration early in a window without consuming it: one 2^22
    # pallas probe, plus the de-aliased shift3 variant only when the
    # pallas probe failed (bench's canary ladder at 2^24 does the
    # production variant selection; this is the diagnostic record).
    if quick:
        plan = [(22, 390, ("pallas", "pallas-shift3"))]
    else:
        plan = [(16, 240, ("pallas", "pallas-shift3", "pallas-jroll",
                           "xla")),
                (20, 300, ("pallas", "pallas-shift3", "pallas-jroll",
                           "xla")),
                (22, 540, ("pallas", "xla")),
                (24, 600, ("pallas", "xla"))]
    try:
        for log2, budget, modes in plan:
            pallas_clean = False
            for mode in modes:
                if quick and mode != "pallas" and pallas_clean:
                    continue   # nothing to bisect: default mode works
                res = run(log2, mode, timeout_s=budget)
                append(json.dumps(res) + "\n")
                print(json.dumps(res), flush=True)
                if mode == "pallas" and "rc" not in res:
                    pallas_clean = True
                last = (log2, mode) == (plan[-1][0], plan[-1][2][-1])
                if mode.startswith("pallas") and "rc" in res and not last:
                    # crash or timeout: the worker may be down; pause
                    # so the next row isn't poisoned by recovery
                    time.sleep(45)
    finally:
        append("```\n")


if __name__ == "__main__":
    main()
