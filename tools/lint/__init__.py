# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""sparselint — the repo's rule-based AST static-analysis suite.

The codebase's hardest-won invariants (host syncs stay out of traced
code, guarded module globals are touched under their lock, settings
mutations bump the plan-cache epoch, every env knob and obs name has a
docs row, wall-clock never times latency/deadline/breaker paths) used
to be enforced by convention plus three ad-hoc checkers.  This package
makes them a framework: a rule registry, per-finding ``file:line``
output with severity and rule id, inline ``# lint: disable=<rule>``
suppressions, a committed baseline for grandfathered findings, and
human/JSON output with deterministic exit codes.

Entry points:

- ``tools/sparselint.py`` — the CLI (full scan, ``--changed``,
  ``--json``, ``--update-baseline``).
- ``tools.lint.core.run_lint`` — the library API (tests use it).
- ``tools/check_fault_sites.py`` / ``check_obs_docs.py`` /
  ``check_kernel_registry.py`` — thin back-compat wrappers over the
  migrated rules, exit semantics unchanged.

See ``docs/LINT.md`` for the rule catalog and workflows.
"""

from .core import (  # noqa: F401
    Finding, Rule, Context, all_rules, get_rule, register, run_lint,
)
from . import rules  # noqa: F401  (importing registers every rule)
