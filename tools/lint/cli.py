# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""sparselint CLI (see ``tools/sparselint.py`` for the entry shim).

Exit codes are deterministic: 0 = no unsuppressed, un-baselined
findings; 1 = findings; 2 = usage/internal error (argparse's own
convention for usage errors).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import (
    Context, DEFAULT_BASELINE, all_rules, load_baseline, run_lint,
    write_baseline,
)


def changed_files(repo: str):
    """Repo-relative paths touched vs HEAD (unstaged + staged +
    untracked) — the fast pre-commit selection."""
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others",
                  "--exclude-standard"]):
        try:
            text = subprocess.run(
                args, cwd=repo, capture_output=True, text=True,
                check=True).stdout
        except (OSError, subprocess.CalledProcessError) as e:
            raise RuntimeError(f"--changed needs git: {e}") from e
        out.update(l.strip() for l in text.splitlines() if l.strip())
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sparselint",
        description="Rule-based AST static analysis for the repo's "
                    "trace-purity / lock-discipline / settings-epoch "
                    "/ knob-and-name-registry invariants "
                    "(docs/LINT.md).")
    ap.add_argument("paths", nargs="*",
                    help="restrict the scan to these repo-relative "
                         "files/dirs (default: each rule's full "
                         "scope)")
    ap.add_argument("--changed", action="store_true",
                    help="scan only git-diff-touched files (pre-commit "
                         "mode); whole-program rules re-run when a "
                         "file in their scope changed")
    ap.add_argument("--rules",
                    help="comma-separated rule ids to run (default: "
                         "all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings artifact on "
                         "stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: "
                         "tools/lint/baseline.json); 'none' disables")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current "
                         "unsuppressed findings and exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    ctx = Context()
    rules = all_rules()

    if args.list_rules:
        width = max(len(r) for r in rules)
        for rid in sorted(rules):
            print(f"{rid.ljust(width)}  {rules[rid].description}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",")
                    if r.strip()]
        unknown = sorted(set(rule_ids) - set(rules))
        if unknown:
            print(f"sparselint: unknown rule(s): "
                  f"{', '.join(unknown)}", file=sys.stderr)
            return 2

    selection = None
    if args.changed:
        try:
            selection = changed_files(ctx.repo)
        except RuntimeError as e:
            print(f"sparselint: {e}", file=sys.stderr)
            return 2
    elif args.paths:
        selection = []
        for p in args.paths:
            rel = os.path.relpath(
                os.path.abspath(p), ctx.repo).replace(os.sep, "/")
            if os.path.isdir(ctx.abspath(rel)):
                selection.extend(ctx.py_files(rel))
            else:
                selection.append(rel)

    baseline = None if args.baseline == "none" else args.baseline
    if args.update_baseline:
        res = run_lint(ctx, selection=selection, rule_ids=rule_ids,
                       baseline_path=None)
        write_baseline(baseline or DEFAULT_BASELINE,
                       res.active)
        print(f"sparselint: baseline rewritten with "
              f"{len(res.active)} entry(ies) -> "
              f"{baseline or DEFAULT_BASELINE}")
        return 0

    res = run_lint(ctx, selection=selection, rule_ids=rule_ids,
                   baseline_path=baseline)

    if args.as_json:
        print(json.dumps(res.to_json(), indent=1, sort_keys=True))
        return res.exit_code

    for f in res.active:
        print(f.render())
    for key in res.stale_baseline:
        print(f"sparselint: stale baseline entry {key!r} matched "
              f"nothing — remove it", file=sys.stderr)
    n_sup, n_base = len(res.suppressed), len(res.baselined)
    extras = []
    if n_sup:
        extras.append(f"{n_sup} suppressed inline")
    if n_base:
        extras.append(f"{n_base} baselined")
    extra = f" ({', '.join(extras)})" if extras else ""
    if res.active:
        print(f"sparselint: FAILED — {len(res.active)} finding(s) "
              f"across {len(res.rules_run)} rule(s){extra}",
              file=sys.stderr)
        return 1
    print(f"sparselint: OK — 0 findings across "
          f"{len(res.rules_run)} rule(s), "
          f"{len(res.files_scanned)} file(s){extra}")
    return 0
