# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""sparselint core: findings, rule registry, suppressions, baseline,
and the runner.

Design notes
------------
- A **rule** is a class with a stable ``id`` (kebab-case), a severity,
  a one-line description, and a ``check(ctx, files)`` method yielding
  ``Finding``s.  Rules register themselves via the ``@register``
  decorator at import (``tools.lint.rules`` imports every rule
  module); the registry is the single source of truth the CLI, the
  falsifiability drill and the docs catalog all read.
- **Scope**: each rule declares the repo-relative path prefixes it
  reads (``scope_prefixes``) plus any non-Python inputs
  (``doc_inputs`` — README/docs tables for the registry-gate rules).
  The runner intersects a file selection (explicit paths or
  ``--changed``) with each rule's scope; whole-program rules
  (``whole_program = True``) run against their full scope whenever the
  selection touches it, because their findings are properties of the
  program, not of one file.
- **Suppression** is inline and line-scoped: a trailing
  ``# lint: disable=<rule>[,<rule>...]`` (or ``disable=all``) on the
  finding's line silences it.  Suppressed findings are still counted
  and reported in the summary — silence is visible, never free.
- **Baseline**: grandfathered findings live in a committed JSON file
  keyed ``(rule, path, message)`` — deliberately line-number-free so
  unrelated edits above a grandfathered site don't resurrect it.
  Entries that match nothing are reported as *stale* (warning, not a
  failure) so the baseline shrinks instead of rotting.
- **Falsifiability**: every rule carries a known-bad fixture under
  ``tools/lint/fixtures/`` (or a synthetic-input override) and a
  ``falsifiability(ctx)`` hook that must produce at least one finding.
  ``tests/test_lint.py`` drills every registered rule through it — a
  rule that cannot fire is a rule that checks nothing, the same
  own-module-excluded discipline the legacy checkers established.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Shared finding/baseline core (tools/common): one definition serves
# both sparselint and planverify, so the two gates render findings and
# grandfather baselines identically.  Re-exported here because every
# rule module and tests/test_lint.py import them from this module.
from ..common.findings import (  # noqa: F401
    Finding, load_baseline, write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG_PREFIX = "legate_sparse_tpu/"
FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures")
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")

SEVERITIES = ("error", "warning")

# Inline suppression: ``# lint: disable=rule-a,rule-b`` (or ``all``),
# anywhere in the finding's source line.  A justification after the
# rule list is encouraged: ``# lint: disable=monotonic-clock — file
# mtimes are wall-clock``.
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_,\-]+)")


class Context:
    """Shared per-run state: repo root plus cached sources/ASTs."""

    def __init__(self, repo: str = REPO):
        self.repo = repo
        self._sources: Dict[str, str] = {}
        self._trees: Dict[str, ast.AST] = {}

    def abspath(self, rel: str) -> str:
        return os.path.join(self.repo, rel.replace("/", os.sep))

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            with open(self.abspath(rel)) as f:
                self._sources[rel] = f.read()
        return self._sources[rel]

    def source_lines(self, rel: str) -> List[str]:
        return self.source(rel).splitlines()

    def tree(self, rel: str) -> ast.AST:
        """Parsed AST with parent links (``_lint_parent``)."""
        if rel not in self._trees:
            tree = ast.parse(self.source(rel), filename=rel)
            for node in ast.walk(tree):
                for child in ast.iter_child_nodes(node):
                    child._lint_parent = node
            self._trees[rel] = tree
        return self._trees[rel]

    def py_files(self, prefix: str) -> List[str]:
        """Repo-relative .py paths under ``prefix`` (sorted,
        ``__pycache__`` excluded)."""
        root = self.abspath(prefix)
        out = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.repo)
                    out.append(rel.replace(os.sep, "/"))
        return sorted(out)


class Rule:
    """Base class; subclasses register with ``@register``."""

    id: str = ""
    severity: str = "error"
    description: str = ""
    # Repo-relative prefixes of the Python sources this rule reads.
    scope_prefixes: Tuple[str, ...] = (PKG_PREFIX,)
    # Non-Python inputs (docs tables etc.) whose edits re-trigger the
    # rule under --changed.
    doc_inputs: Tuple[str, ...] = ()
    # Whole-program rules check cross-file properties: under a file
    # selection they run over their FULL scope once any selected file
    # triggers them.
    whole_program: bool = False
    # Known-bad fixture (repo-relative) for the falsifiability drill;
    # rules with synthetic-input drills override falsifiability().
    bad_fixture: Optional[str] = None

    def scope_files(self, ctx: Context) -> List[str]:
        out: List[str] = []
        for p in self.scope_prefixes:
            if p.endswith(".py"):
                out.append(p)
            else:
                out.extend(ctx.py_files(p))
        return out

    def triggers(self, rel: str) -> bool:
        """Does an edit to ``rel`` warrant re-running this rule?"""
        return rel in self.doc_inputs or any(
            rel.startswith(p) or rel == p for p in self.scope_prefixes)

    def check(self, ctx: Context, files: Sequence[str]
              ) -> Iterable[Finding]:
        raise NotImplementedError

    def falsifiability(self, ctx: Context) -> List[Finding]:
        """Findings on the rule's seeded known-bad input.  Must be
        non-empty — drilled by tests/test_lint.py."""
        if self.bad_fixture is None:
            raise NotImplementedError(
                f"rule {self.id} has neither a bad_fixture nor a "
                f"falsifiability override")
        return list(self.check(ctx, [self.bad_fixture]))


_RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding an instance to the registry."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule class {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    return dict(_RULES)


def get_rule(rule_id: str) -> Rule:
    return _RULES[rule_id]


# ------------------------------------------------------------------ #
# suppression
# ------------------------------------------------------------------ #

def suppressed_by_line(ctx: Context, finding: Finding) -> bool:
    """True when the finding's source line carries a matching inline
    ``# lint: disable=`` comment."""
    if finding.line <= 0:
        return False
    try:
        lines = ctx.source_lines(finding.path)
    except OSError:
        return False
    if finding.line > len(lines):
        return False
    m = _SUPPRESS_RE.search(lines[finding.line - 1])
    if not m:
        return False
    names = {tok.strip() for tok in m.group(1).split(",")}
    return finding.rule in names or "all" in names


# ------------------------------------------------------------------ #
# runner
# ------------------------------------------------------------------ #

@dataclass
class Result:
    """One lint run's outcome, pre-split by disposition."""

    active: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[Tuple[str, str, str]] = field(
        default_factory=list)
    rules_run: List[str] = field(default_factory=list)
    files_scanned: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.active else 0

    def to_json(self) -> dict:
        return {
            "version": 1,
            "findings": [asdict(f) for f in self.active],
            "suppressed": [asdict(f) for f in self.suppressed],
            "baselined": [asdict(f) for f in self.baselined],
            "stale_baseline": [
                {"rule": r, "path": p, "message": m}
                for (r, p, m) in self.stale_baseline],
            "rules_run": self.rules_run,
            "files_scanned": self.files_scanned,
            "exit_code": self.exit_code,
        }


def run_lint(ctx: Optional[Context] = None,
             selection: Optional[Sequence[str]] = None,
             rule_ids: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE) -> Result:
    """Run rules and classify findings.

    ``selection`` restricts to repo-relative files (``--changed`` /
    explicit CLI paths); ``None`` = full scan.  ``rule_ids`` restricts
    the rule set.  ``baseline_path=None`` disables baselining.
    """
    ctx = ctx or Context()
    rules = [_RULES[r] for r in rule_ids] if rule_ids else (
        [_RULES[k] for k in sorted(_RULES)])
    sel = None
    if selection is not None:
        sel = {s.replace(os.sep, "/") for s in selection}

    res = Result()
    baseline = load_baseline(baseline_path) if baseline_path else {}
    consumed: Dict[Tuple[str, str, str], int] = {}

    for rule in rules:
        scope = rule.scope_files(ctx)
        if sel is None:
            files = scope
        else:
            if not any(rule.triggers(s) for s in sel):
                continue
            files = scope if rule.whole_program else [
                f for f in scope if f in sel]
            if not files:
                continue
        res.rules_run.append(rule.id)
        res.files_scanned.extend(
            f for f in files if f not in res.files_scanned)
        for f in sorted(rule.check(ctx, files),
                        key=lambda f: (f.path, f.line, f.rule)):
            if suppressed_by_line(ctx, f):
                res.suppressed.append(f)
            elif baseline.get(f.baseline_key(), 0) > consumed.get(
                    f.baseline_key(), 0):
                consumed[f.baseline_key()] = consumed.get(
                    f.baseline_key(), 0) + 1
                res.baselined.append(f)
            else:
                res.active.append(f)

    for key, n in sorted(baseline.items()):
        if consumed.get(key, 0) < n:
            res.stale_baseline.append(key)
    return res
