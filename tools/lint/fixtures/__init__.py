# Known-bad snippets for the sparselint falsifiability drill.  These
# files are PARSED, never imported, and live outside every rule's
# default scan scope — each exists so tests/test_lint.py can prove its
# rule still fires (a rule that cannot fire checks nothing).
