# Known-bad fixture for the knob-registry rule (parsed, never run).
import os

# BAD: no README/docs env-table row documents this knob.
_UNDOCUMENTED = os.environ.get("LEGATE_SPARSE_TPU_ZZ_UNDOCUMENTED")

# OK: documented knob.
_DOCUMENTED = os.environ.get("LEGATE_SPARSE_TPU_OBS")
