# Known-bad fixture for the lock-discipline rule (parsed, never run).
# The falsifiability drill registers {"_LOCK": {"_STATE"}} for this
# file and expects findings on the unlocked accesses only.
import threading

_LOCK = threading.Lock()
_STATE = {}                  # module-level init: exempt


def bad_write(key, value):
    _STATE[key] = value      # BAD: write outside 'with _LOCK:'


def bad_read(key):
    return _STATE.get(key)   # BAD: read outside 'with _LOCK:'


def good_write(key, value):
    with _LOCK:
        _STATE[key] = value  # OK: under the declared lock


def shadowed(_STATE):
    return _STATE            # OK: parameter shadows the global
