# Known-bad fixture for the monotonic-clock rule (parsed, never run).
import time


def bad_deadline(budget_s):
    start = time.time()      # BAD: wall clock in a timing path
    return time.time() - start > budget_s


def good_deadline(budget_s):
    start = time.monotonic()
    return time.monotonic() - start > budget_s
