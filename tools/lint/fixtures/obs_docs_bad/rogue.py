# Known-bad fixture for the obs-docs rule (parsed, never run): this
# directory stands in for the package root in the falsifiability
# drill, and the emission below is covered by no OBSERVABILITY.md row.
_obs = None  # the regex keys on the receiver/method shape, not types


def rogue():
    _obs.inc("zz.totally_undocumented_emission")
