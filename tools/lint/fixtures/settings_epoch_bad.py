# Known-bad fixture for the settings-epoch rule (parsed, never run).
from legate_sparse_tpu.settings import settings


def bad_bypass():
    settings.__dict__["ell_max_expand"] = 0.0   # BAD: epoch bypass
    object.__setattr__(settings, "x64", False)  # BAD: epoch bypass
    vars(settings)["resil"] = True              # BAD: epoch bypass


def bad_typo():
    return settings.not_a_real_knob             # BAD: unknown attr


def good_mutation():
    settings.ell_max_expand = 2.0   # OK: goes through __setattr__
    return settings.epoch           # OK: declared property
