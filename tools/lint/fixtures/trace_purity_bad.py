# Known-bad fixture for the trace-purity rule (parsed, never run).
import time

import jax
import numpy as np
from jax import lax


@jax.jit
def bad_jitted(x):
    print("trace-time only", x)     # BAD: print in traced code
    y = float(x)                    # BAD: coerces a traced argument
    return y + x.item()             # BAD: .item() host sync


def bad_loop(x0):
    def cond(c):
        return bool(c)              # BAD: bool() on a traced param

    def body(c):
        np.asarray(c)               # BAD: host materialization
        time.time()                 # BAD: trace-time clock read
        return c + 1

    return lax.while_loop(cond, body, x0)


def good_host_code(x):
    # Host-side code may do all of this freely — no findings here.
    print("host", float(np.asarray(x).item()), time.time())
    return int(np.ceil(x))
