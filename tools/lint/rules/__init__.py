# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Rule modules.  Importing this package registers every rule with
``tools.lint.core`` — the CLI, the falsifiability drill and docs/LINT.md
all enumerate the same registry."""

from . import (  # noqa: F401
    fault_sites,
    kernel_registry,
    knob_registry,
    lock_discipline,
    monotonic_clock,
    obs_docs,
    plan_contract,
    settings_epoch,
    trace_purity,
)
