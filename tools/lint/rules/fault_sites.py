# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""fault-sites: the fault-injection site catalog stays wired.

Migrated from the ad-hoc ``tools/check_fault_sites.py`` (which remains
as a thin CLI wrapper with identical exit semantics).  The three views
of the site list — the code's ``fault_point(...)`` literals,
``resilience.faults.CATALOG``, and the ``docs/RESILIENCE.md`` site
table — must agree; see the wrapper docstring for the four sub-checks.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Sequence, Tuple

from ..core import Context, Finding, PKG_PREFIX, Rule, register

DOC_REL = "docs/RESILIENCE.md"
FAULTS_REL = "legate_sparse_tpu/resilience/faults.py"

# A quoted dotted lowercase name passed as the first argument of one
# of the site-taking entry points.  ``\brun\(`` deliberately also
# matches ``policy.run(``/``_rpolicy.run(``; the dotted-name shape
# keeps unrelated ``run(`` calls (subprocess etc.) out.
SITE_CALL_RE = re.compile(
    r"(?:fault_point|guarded_call|_resil_guarded|\brun)\(\s*\n?\s*"
    r"[\"']([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)[\"']")


def collect_call_sites(catalog, pkg_dir: str, repo: str):
    """{site: [relpath, ...]} for every site literal at an entry
    point, plus {site: count} of raw quoted occurrences anywhere."""
    calls: Dict[str, List[str]] = {}
    quoted: Dict[str, int] = {}
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                text = f.read()
            rel = os.path.relpath(path, repo)
            for site in SITE_CALL_RE.findall(text):
                calls.setdefault(site, []).append(rel)
            if rel.replace(os.sep, "/") == FAULTS_REL:
                # The catalog's own module quotes every site by
                # definition; counting it would make orphan detection
                # (rule 2) unable to ever fire.
                continue
            for site in catalog:
                if f'"{site}"' in text or f"'{site}'" in text:
                    quoted[site] = quoted.get(site, 0) + 1
    return calls, quoted


def problems_for(catalog, default_sites, pkg_dir: str, doc_path: str,
                 repo: str) -> Tuple[List[Tuple[str, str]], dict]:
    """[(message, attributed-relpath)] in the legacy wording, plus the
    call-site map for ``--list``."""
    calls, quoted = collect_call_sites(catalog, pkg_dir, repo)
    problems: List[Tuple[str, str]] = []

    for site in sorted(set(calls) - set(catalog)):
        files = sorted(set(calls[site]))
        problems.append((
            f"call site uses unregistered name {site!r} "
            f"(in {', '.join(files)}) — add it to "
            f"resilience.faults.CATALOG",
            files[0].replace(os.sep, "/")))

    for site in sorted(s for s in catalog if not quoted.get(s)):
        problems.append((
            f"catalog site {site!r} has NO call-site literal in the "
            f"package — injection coverage rotted", FAULTS_REL))

    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        doc = ""
        problems.append((f"docs/RESILIENCE.md unreadable: {e}",
                         DOC_REL))
    for site in sorted(s for s in catalog if s not in doc):
        problems.append((
            f"catalog site {site!r} missing from docs/RESILIENCE.md",
            DOC_REL))

    for site in sorted(set(default_sites) - set(catalog)):
        problems.append((
            f"chaos.DEFAULT_SITES entry {site!r} is not a catalog "
            f"site — the drill would arm a hook nobody calls",
            "legate_sparse_tpu/resilience/chaos.py"))

    return problems, calls


@register
class FaultSitesRule(Rule):
    id = "fault-sites"
    description = ("fault_point literals, resilience.faults.CATALOG "
                   "and the docs/RESILIENCE.md site table must agree "
                   "(legacy check_fault_sites)")
    scope_prefixes = (PKG_PREFIX,)
    doc_inputs = (DOC_REL,)
    whole_program = True

    def check(self, ctx: Context, files: Sequence[str],
              catalog=None, default_sites=None) -> Iterable[Finding]:
        if catalog is None or default_sites is None:
            import sys
            if ctx.repo not in sys.path:
                sys.path.insert(0, ctx.repo)
            from legate_sparse_tpu.resilience.chaos import \
                DEFAULT_SITES as _ds
            from legate_sparse_tpu.resilience.faults import \
                CATALOG as _cat
            catalog = _cat if catalog is None else catalog
            default_sites = _ds if default_sites is None \
                else default_sites
        problems, _calls = problems_for(
            catalog, default_sites, ctx.abspath(PKG_PREFIX.rstrip("/")),
            ctx.abspath(DOC_REL), ctx.repo)
        for msg, rel in problems:
            yield Finding(rule="fault-sites", path=rel, line=0,
                          message=msg)

    def falsifiability(self, ctx: Context):
        # Synthetic rot: an orphaned catalog entry (site with no
        # call-site literal) — the exact drill test_resilience runs
        # against the wrapper.
        from legate_sparse_tpu.resilience.chaos import DEFAULT_SITES
        from legate_sparse_tpu.resilience.faults import CATALOG
        catalog = dict(CATALOG)
        catalog["engine.plan.lint_falsifiability_probe"] = "synthetic"
        return list(self.check(ctx, [], catalog=catalog,
                               default_sites=DEFAULT_SITES))
