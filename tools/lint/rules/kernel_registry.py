# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""kernel-registry: the autotune candidate registry stays wired.

Migrated from the ad-hoc ``tools/check_kernel_registry.py`` (which
remains as a thin CLI wrapper with identical exit semantics).  The
three views of the candidate list — ``autotune/registry.py``, the
package's dispatch literals, and the ``docs/AUTOTUNER.md`` candidate
table — must agree; plus the structural invariant that each
``CANDIDATES`` key equals its entry's label.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Sequence, Tuple

from ..core import Context, Finding, PKG_PREFIX, Rule, register

DOC_REL = "docs/AUTOTUNER.md"
REGISTRY_REL = "legate_sparse_tpu/autotune/registry.py"


def collect_literals(candidates, pkg_dir: str, repo: str):
    """{label: [relpath, ...]} of quoted label occurrences outside the
    registry module, plus {kernel: True} for files quoting the
    ``trace.<kernel>`` counter name."""
    quoted: Dict[str, List[str]] = {}
    traced: Dict[str, bool] = {}
    trace_names = {c.kernel: f"trace.{c.kernel}"
                   for c in candidates.values()}
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                text = f.read()
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            for kernel, tname in trace_names.items():
                if f'"{tname}"' in text or f"'{tname}'" in text:
                    traced[kernel] = True
            if rel == REGISTRY_REL:
                # The registry quotes every label by definition;
                # counting it would make orphan detection (rule 2)
                # unable to fire.
                continue
            for label in candidates:
                if f'"{label}"' in text or f"'{label}'" in text:
                    quoted.setdefault(label, []).append(rel)
    return quoted, traced


def problems_for(candidates, spmv_module, pkg_dir: str, doc_path: str,
                 repo: str) -> Tuple[List[Tuple[str, str]], dict]:
    """[(message, attributed-relpath)] in the legacy wording, plus the
    quoted-label map for ``--list``."""
    quoted, traced = collect_literals(candidates, pkg_dir, repo)
    problems: List[Tuple[str, str]] = []

    for key, cand in sorted(candidates.items()):
        if key != cand.label:
            problems.append((
                f"registry key {key!r} != its entry's label "
                f"{cand.label!r} — verdicts store labels, a mismatch "
                f"makes them unroutable", REGISTRY_REL))
        fn = getattr(spmv_module, cand.kernel, None)
        if not callable(fn):
            problems.append((
                f"candidate {cand.label!r} names kernel "
                f"{cand.kernel!r}, which is not a callable in "
                f"legate_sparse_tpu.ops.spmv — registry rotted",
                REGISTRY_REL))
        elif not traced.get(cand.kernel):
            problems.append((
                f"kernel {cand.kernel!r} has no 'trace.{cand.kernel}' "
                f"compile counter in the package — the jitted-kernel "
                f"instrumentation contract is broken",
                "legate_sparse_tpu/ops/spmv.py"))

    for label in sorted(l for l in candidates if not quoted.get(l)):
        problems.append((
            f"candidate label {label!r} has NO quoted literal outside "
            f"the registry — no dispatch site serves it", REGISTRY_REL))

    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        doc = ""
        problems.append((f"docs/AUTOTUNER.md unreadable: {e}",
                         DOC_REL))
    for label in sorted(l for l in candidates if l not in doc):
        problems.append((
            f"candidate label {label!r} missing from "
            f"docs/AUTOTUNER.md", DOC_REL))

    return problems, quoted


@register
class KernelRegistryRule(Rule):
    id = "kernel-registry"
    description = ("autotune CANDIDATES must name real ops.spmv "
                   "kernels with wired trace counters, dispatch-site "
                   "literals and docs rows (legacy "
                   "check_kernel_registry)")
    scope_prefixes = (PKG_PREFIX,)
    doc_inputs = (DOC_REL,)
    whole_program = True

    def check(self, ctx: Context, files: Sequence[str],
              candidates=None, spmv_module=None) -> Iterable[Finding]:
        if candidates is None or spmv_module is None:
            import sys
            if ctx.repo not in sys.path:
                sys.path.insert(0, ctx.repo)
            from legate_sparse_tpu.autotune.registry import CANDIDATES
            from legate_sparse_tpu.ops import spmv as _spmv
            candidates = CANDIDATES if candidates is None \
                else candidates
            spmv_module = _spmv if spmv_module is None else spmv_module
        problems, _ = problems_for(
            candidates, spmv_module,
            ctx.abspath(PKG_PREFIX.rstrip("/")), ctx.abspath(DOC_REL),
            ctx.repo)
        for msg, rel in problems:
            yield Finding(rule="kernel-registry", path=rel, line=0,
                          message=msg)

    def falsifiability(self, ctx: Context):
        # Synthetic rot: a candidate naming a kernel that does not
        # exist in ops.spmv.
        import sys
        if ctx.repo not in sys.path:
            sys.path.insert(0, ctx.repo)
        from legate_sparse_tpu.autotune.registry import (
            CANDIDATES, Candidate)
        from legate_sparse_tpu.ops import spmv as _spmv
        cands = dict(CANDIDATES)
        probe = "zz-lint-falsifiability-probe"
        cands[probe] = Candidate(
            label=probe, kernel="zz_missing_kernel", ops=("spmv",),
            eligible=lambda A: False, run=lambda A, x, op: None)
        return list(self.check(ctx, [], candidates=cands,
                               spmv_module=_spmv))
