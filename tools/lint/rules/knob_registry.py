# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""knob-registry: every ``LEGATE_SPARSE*`` env knob has a docs row.

Generalizes ``check_obs_docs`` from obs names to environment knobs:
each string literal in the package matching ``LEGATE_SPARSE[A-Z0-9_]*``
must appear in the README env table or a ``docs/*.md`` page.  The env
surface is the package's operator API — an undocumented knob is a
feature nobody can discover and a support burden when its spelling is
guessed wrong.

Matching rules, in order:

- a literal ending in ``_`` is a *prefix* (knob-family builders like
  ``LEGATE_SPARSE_TPU_RESIL_``): documented when any doc file contains
  a knob extending it;
- a full name is documented when it appears verbatim in any doc file;
- otherwise a backticked shorthand suffix row (the README's
  ```_PROBE_TIMEOUT` / `_PROBE_RETRIES```-style family rows)
  covers it when the name ends with that suffix token.

Names built entirely at runtime (no literal) are invisible here — the
same stated limitation as the obs-docs pass: keep at least a literal
prefix at knob read sites.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Sequence, Tuple

from ..core import Context, Finding, PKG_PREFIX, Rule, register

KNOB_RE = re.compile(r"LEGATE_SPARSE[A-Z0-9_]*")
# Backticked shorthand suffix tokens in docs (`_PROBE_TIMEOUT`).
SHORTHAND_RE = re.compile(r"`(_[A-Z][A-Z0-9_]*)`")

DOC_PATHS = ("README.md", "docs/OBSERVABILITY.md", "docs/ENGINE.md",
             "docs/RESILIENCE.md", "docs/AUTOTUNER.md", "docs/DIST.md",
             "docs/MIGRATING.md", "docs/LINT.md")


def _doc_text(ctx: Context, doc_paths: Sequence[str]) -> str:
    parts = []
    for rel in doc_paths:
        try:
            parts.append(ctx.source(rel))
        except OSError:
            pass
    return "\n".join(parts)


def collect_knob_literals(ctx: Context, files: Sequence[str]
                          ) -> Dict[str, List[Tuple[str, int]]]:
    """{knob: [(relpath, line), ...]} from string literals (f-string
    literal parts included) in the given files."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for rel in files:
        tree = ctx.tree(rel)
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                for m in KNOB_RE.findall(node.value):
                    out.setdefault(m, []).append((rel, node.lineno))
    return out


def documented(name: str, doc_text: str, shorthands) -> bool:
    if name.endswith("_"):
        # Prefix literal: covered when a documented knob extends it.
        return bool(re.search(re.escape(name) + r"[A-Z0-9]", doc_text))
    if name in doc_text:
        return True
    return any(name.endswith(sh) for sh in shorthands)


@register
class KnobRegistryRule(Rule):
    id = "knob-registry"
    description = ("every LEGATE_SPARSE* env-knob literal in the "
                   "package must have a README/docs env-table row")
    scope_prefixes = (PKG_PREFIX,)
    doc_inputs = DOC_PATHS
    whole_program = True
    bad_fixture = "tools/lint/fixtures/knob_registry_bad.py"

    def check(self, ctx: Context, files: Sequence[str],
              doc_paths: Sequence[str] = DOC_PATHS
              ) -> Iterable[Finding]:
        doc_text = _doc_text(ctx, doc_paths)
        shorthands = set(SHORTHAND_RE.findall(doc_text))
        knobs = collect_knob_literals(ctx, files)
        for name in sorted(knobs):
            if documented(name, doc_text, shorthands):
                continue
            # One finding per knob, at its first occurrence; the rest
            # of the occurrences ride in the message.
            sites = sorted(set(knobs[name]))
            rel, line = sites[0]
            extra = "" if len(sites) == 1 else \
                f" (+{len(sites) - 1} more site(s))"
            yield Finding(
                rule="knob-registry", path=rel, line=line,
                message=(f"env knob {name!r} has no row in the README "
                         f"env table or docs/*.md{extra}"))

    def falsifiability(self, ctx: Context):
        return list(self.check(ctx, [self.bad_fixture]))
