# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""lock-discipline: guarded module globals are touched under their lock.

PR 4 hardened ``dist_spgemm``'s module state behind ``_STATE_LOCK``
after the request executor made concurrent callers a supported
configuration, and the same pattern now guards singletons, registries
and telemetry buffers across the package.  The discipline rots the
usual way: a new helper reads or writes the global without the ``with``
block, works in every single-threaded test, and tears under load.

``REGISTRY`` below *declares* which lock guards which module globals —
seeded from every module currently using the ``_STATE_LOCK``-style
pattern.  The rule then flags any read or write of a registered global
from inside a function in that module that is not lexically within a
``with <lock>:`` block.

Module-level statements (the definitions and initializers themselves)
are exempt: they run at import, before any concurrency exists.  So are
functions whose name ends in ``_locked`` — the package's existing
convention for helpers whose contract is "caller holds the lock"
(``counters._compact_locked``, ``latency._merged_locked``); the naming
IS the declaration, and the rule enforces that the convention stays
spelled out.  Deliberate unlocked access — double-checked fast paths,
GIL-atomic single-reference reads — carries an inline
``# lint: disable=lock-discipline`` with a one-line justification,
which doubles as documentation of the memory-model argument.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Sequence, Set

from ..core import Context, Finding, Rule, register

# {module relpath: {lock name: frozenset(guarded globals)}} — the
# declared closed registry.  Adding a guarded global to a module means
# adding it here; the falsifiability fixture proves the rule fires.
REGISTRY: Dict[str, Dict[str, frozenset]] = {
    "legate_sparse_tpu/parallel/dist_spgemm.py": {
        "_STATE_LOCK": frozenset({
            "_WINDOW_DECLINED", "LAST_B_REALIZATION", "LAST_B_PLAN"}),
    },
    "legate_sparse_tpu/obs/trace.py": {
        "_lock": frozenset({"_records", "_seq_by_name"}),
    },
    "legate_sparse_tpu/obs/counters.py": {
        "_lock": frozenset({"_counters"}),
    },
    "legate_sparse_tpu/obs/latency.py": {
        "_lock": frozenset({"_handles", "_folded"}),
    },
    "legate_sparse_tpu/engine/core.py": {
        "_engine_lock": frozenset({"_engine"}),
    },
    "legate_sparse_tpu/engine/gateway.py": {
        "_gateway_lock": frozenset({"_gateway"}),
    },
    "legate_sparse_tpu/engine/plan_cache.py": {
        "_persist_lock": frozenset({"_persist_enabled"}),
    },
    "legate_sparse_tpu/autotune/__init__.py": {
        "_store_lock": frozenset({"_store"}),
    },
    "legate_sparse_tpu/resilience/faults.py": {
        "_lock": frozenset({"_arms"}),
    },
    "legate_sparse_tpu/resilience/policy.py": {
        "_registry_lock": frozenset({"_breakers", "_budgets"}),
    },
}


def _inside_function(node: ast.AST) -> bool:
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return True
        cur = getattr(cur, "_lint_parent", None)
    return False


def _in_locked_helper(node: ast.AST) -> bool:
    """True inside a ``*_locked``-suffixed function — the declared
    caller-holds-the-lock convention."""
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and cur.name.endswith("_locked"):
            return True
        cur = getattr(cur, "_lint_parent", None)
    return False


def _under_lock(node: ast.AST, lock: str) -> bool:
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id == lock:
                    return True
                # self-style or attribute-qualified lock names
                if isinstance(expr, ast.Attribute) and \
                        expr.attr == lock:
                    return True
        cur = getattr(cur, "_lint_parent", None)
    return False


def _shadowed(node: ast.AST, name: str) -> bool:
    """True when ``name`` is a parameter or local of an enclosing
    function that did NOT declare ``global name`` — then the Name is
    not the module global at all."""
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            a = cur.args
            params = {x.arg for x in
                      (a.posonlyargs + a.args + a.kwonlyargs)}
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
            if name in params:
                return True
            break
        cur = getattr(cur, "_lint_parent", None)
    return False


def check_module(ctx: Context, rel: str,
                 guards: Dict[str, frozenset]) -> Iterable[Finding]:
    tree = ctx.tree(rel)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Name):
            continue
        for lock, names in guards.items():
            if node.id not in names:
                continue
            if not _inside_function(node):
                continue        # import-time definition/initializer
            if _under_lock(node, lock):
                continue
            if _in_locked_helper(node):
                continue        # "*_locked" = caller holds the lock
            if _shadowed(node, node.id):
                continue
            kind = ("write" if isinstance(node.ctx,
                                          (ast.Store, ast.Del))
                    else "read")
            yield Finding(
                rule="lock-discipline", path=rel, line=node.lineno,
                message=(f"{kind} of guarded global {node.id!r} "
                         f"outside 'with {lock}:'"))


@register
class LockDisciplineRule(Rule):
    id = "lock-discipline"
    description = ("registered module globals accessed outside their "
                   "declared lock's 'with' block")
    scope_prefixes = tuple(sorted(REGISTRY))
    bad_fixture = "tools/lint/fixtures/lock_discipline_bad.py"

    def check(self, ctx: Context, files: Sequence[str],
              registry: Dict[str, Dict[str, frozenset]] = None
              ) -> Iterable[Finding]:
        reg = REGISTRY if registry is None else registry
        for rel in files:
            guards = reg.get(rel)
            if guards:
                yield from check_module(ctx, rel, guards)

    def falsifiability(self, ctx: Context):
        fixture = self.bad_fixture
        synthetic = {fixture: {"_LOCK": frozenset({"_STATE"})}}
        return list(self.check(ctx, [fixture], registry=synthetic))
