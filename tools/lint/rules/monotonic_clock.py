# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""monotonic-clock: ``time.time()`` is banned in the package.

PR 8 re-based ``CircuitBreaker`` and ``Deadline`` on
``time.monotonic_ns()`` after establishing that wall-clock timing in
latency/deadline/breaker paths breaks under NTP steps and clock
slew: a deadline computed from ``time.time()`` can expire requests
spuriously (or never) when the clock jumps.  This rule keeps the ban
from regressing: any ``time.time()`` call inside
``legate_sparse_tpu/`` is a finding.

The one legitimate use is comparing against *file* timestamps —
``_platform.py``'s probe-cache TTL compares to an ``st['ts']`` it
itself recorded as wall-clock epoch seconds, shared with the external
``tunnel_watch.sh``.  That site carries an inline justified
suppression, which is exactly the documentation the exception needs.
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence

from ..core import Context, Finding, PKG_PREFIX, Rule, register


@register
class MonotonicClockRule(Rule):
    id = "monotonic-clock"
    description = ("time.time() banned in the package (latency/"
                   "deadline/breaker paths need monotonic clocks)")
    scope_prefixes = (PKG_PREFIX,)
    bad_fixture = "tools/lint/fixtures/monotonic_clock_bad.py"

    def check(self, ctx: Context, files: Sequence[str]
              ) -> Iterable[Finding]:
        for rel in files:
            for node in ast.walk(ctx.tree(rel)):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "time" and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "time":
                    yield Finding(
                        rule="monotonic-clock", path=rel,
                        line=node.lineno,
                        message=("time.time() is wall-clock — use "
                                 "time.monotonic()/monotonic_ns() "
                                 "(or suppress with a justification "
                                 "for true epoch-timestamp uses)"))
