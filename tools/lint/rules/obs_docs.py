# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""obs-docs: every emitted obs name is covered by docs/OBSERVABILITY.md.

Migrated from the ad-hoc ``tools/check_obs_docs.py`` (which remains as
a thin CLI wrapper with identical exit semantics).  Extracts every
name literal passed to an obs emission entry point — counters
(``inc``/``handle``), spans (``span``/``complete_span``), events
(``event``), latency histograms (``observe``/``handle``/``timer``) —
and fails unless each appears in docs/OBSERVABILITY.md verbatim or via
a documented prefix pattern (``resil.*`` / ``mem.<phase>`` tokens).
f-strings contribute their literal prefix; fully-dynamic names are
invisible (keep a literal prefix at emission sites).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Sequence, Tuple

from ..core import Context, Finding, PKG_PREFIX, Rule, register

DOC_REL = "docs/OBSERVABILITY.md"

# A quoted (optionally f-string) name as the first argument of an obs
# emission entry point.  The receiver alternatives cover the package's
# import aliases (obs / _obs / counters / _counters / trace / _trace /
# latency / _latency / _lat); the emission methods are the closed set
# of name-taking APIs.
EMIT_RE = re.compile(
    r"(?:\b(?:_?obs|_?counters|_?trace|_?latency|_lat)\.)"
    r"(?:inc|span|event|handle|observe|timer|complete_span)\(\s*\n?\s*"
    r"(f?)[\"']([^\"'\n]+)[\"']")

# Backticked tokens in the doc that look like emission names: dotted
# lowercase (counters/histograms/events) or bare span names.
DOC_TOKEN_RE = re.compile(r"`([A-Za-z0-9_.<>*/-]+)`")


def collect_emissions(pkg_dir: str, repo: str):
    """{(name_or_prefix, is_prefix): [relpath, ...]} of emitted name
    literals; f-string names reduce to their literal prefix."""
    out: Dict[Tuple[str, bool], List[str]] = {}
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                text = f.read()
            rel = os.path.relpath(path, repo)
            for fprefix, raw in EMIT_RE.findall(text):
                name = raw
                is_prefix = False
                if fprefix:
                    cut = raw.find("{")
                    if cut == 0:
                        continue    # no literal prefix: invisible here
                    if cut > 0:
                        name = raw[:cut]
                        is_prefix = True
                # Concatenated-literal emissions ("lat.spmv." +
                # shape_bucket(...)) present as a trailing-dot literal
                # — treat like an f-string prefix.
                if name.endswith("."):
                    is_prefix = True
                if not re.match(r"^[a-z][a-zA-Z0-9_.]*\.?$", name):
                    continue    # not an emission name (messages etc.)
                out.setdefault((name, is_prefix), []).append(rel)
    return out


def doc_patterns(doc_text: str):
    """(exact_names, prefixes) from the doc's backticked tokens.  A
    token ending in ``*`` or containing a ``<placeholder>`` segment
    contributes its literal head as a prefix pattern."""
    exact = set()
    prefixes = set()
    for tok in DOC_TOKEN_RE.findall(doc_text):
        cut = len(tok)
        for ch in ("*", "<"):
            pos = tok.find(ch)
            if pos != -1:
                cut = min(cut, pos)
        if cut < len(tok):
            head = tok[:cut]
            if head:
                prefixes.add(head)
        else:
            exact.add(tok)
    return exact, prefixes


def documented(name: str, is_prefix: bool, exact, prefixes) -> bool:
    if not is_prefix and name in exact:
        return True
    for p in prefixes:
        if name.startswith(p):
            return True
    if is_prefix:
        # An f-string prefix is covered when some documented exact
        # name or pattern head extends it (the doc names the family).
        for t in exact:
            if t.startswith(name):
                return True
        for p in prefixes:
            if p.startswith(name):
                return True
    return False


def problems_for(pkg_dir: str, doc_path: str, repo: str):
    """([(message, attributed-relpath)], emissions) in the legacy
    wording; an unreadable doc is a single problem entry."""
    emissions = collect_emissions(pkg_dir, repo)
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return ([(f"docs/OBSERVABILITY.md unreadable: {e}", DOC_REL)],
                emissions)
    exact, prefixes = doc_patterns(doc)

    problems = []
    for (name, is_prefix), where in sorted(emissions.items()):
        if not documented(name, is_prefix, exact, prefixes):
            kind = "prefix" if is_prefix else "name"
            files = sorted(set(where))
            problems.append((
                f"emitted {kind} {name!r} (in {', '.join(files)}) is "
                f"not covered by any docs/OBSERVABILITY.md entry",
                files[0].replace(os.sep, "/")))
    return problems, emissions


@register
class ObsDocsRule(Rule):
    id = "obs-docs"
    description = ("every obs.inc/span/event/observe/timer name "
                   "literal must be covered by docs/OBSERVABILITY.md "
                   "(legacy check_obs_docs)")
    scope_prefixes = (PKG_PREFIX,)
    doc_inputs = (DOC_REL,)
    whole_program = True

    def check(self, ctx: Context, files: Sequence[str],
              pkg_dir: str = None, doc_path: str = None
              ) -> Iterable[Finding]:
        pkg = pkg_dir or ctx.abspath(PKG_PREFIX.rstrip("/"))
        doc = doc_path or ctx.abspath(DOC_REL)
        problems, _ = problems_for(pkg, doc, ctx.repo)
        for msg, rel in problems:
            yield Finding(rule="obs-docs", path=rel, line=0,
                          message=msg)

    def falsifiability(self, ctx: Context):
        # The fixture dir stands in for the package: one undocumented
        # emission literal must fire.
        fixture_pkg = os.path.join(
            ctx.repo, "tools", "lint", "fixtures", "obs_docs_bad")
        return list(self.check(ctx, [], pkg_dir=fixture_pkg))
