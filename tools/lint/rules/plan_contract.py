# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""plan-contract: every dispatchable program has a committed
planverify contract.

The planverify gate (tools/verify/, docs/VERIFY.md) can only hold the
line on programs it knows about.  This rule closes the coverage loop
statically — no jax, no lowering: every kernel label in
``autotune/registry.py`` and every plan-shape triple in
``parallel/dist_csr.py::DIST_PLAN_SHAPES`` /
``parallel/dist_spgemm.py::SPGEMM_PLAN_SHAPES`` must map (via the
shared mechanical filename scheme in ``tools.verify.contracts``) to at
least one committed contract file, and no committed contract may be an
orphan that matches neither — a stale file asserts invariants about a
program that no longer exists.

The plan-shape tuples are read with ``ast.literal_eval`` from the
module source (they are declared as pure literals precisely so this
rule and planverify's catalog can enumerate them without devices).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Tuple

from ...verify.contracts import (
    dist_prefix, kernel_prefix, list_contracts,
)
from ..core import Context, Finding, Rule, register

REGISTRY_REL = "legate_sparse_tpu/autotune/registry.py"
DIST_REL = "legate_sparse_tpu/parallel/dist_csr.py"
SPGEMM_REL = "legate_sparse_tpu/parallel/dist_spgemm.py"
CONTRACTS_REL = "tools/verify/contracts/"

_UPDATE_HINT = ("run `python tools/planverify.py --update-contracts "
                "--reason '...'` after adding the program to "
                "tools/verify/catalog.py")


def registry_labels(ctx: Context) -> List[str]:
    """Kernel labels from the registry source: every ``label="..."``
    keyword (the kernel-registry rule separately enforces that keys
    and labels agree, so the keyword set IS the label set)."""
    tree = ctx.tree(REGISTRY_REL)
    labels = []
    for node in ast.walk(tree):
        if isinstance(node, ast.keyword) and node.arg == "label" and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            labels.append(node.value.value)
    return sorted(set(labels))


def plan_shape_literals(ctx: Context, rel: str, name: str
                        ) -> Optional[Tuple]:
    """``ast.literal_eval`` of module-level ``name = (...)`` in
    ``rel``; None when the assignment is missing or not a literal."""
    tree = ctx.tree(rel)
    for node in tree.body if hasattr(tree, "body") else []:
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            targets = [node.target.id]
        else:
            continue
        if name in targets and node.value is not None:
            try:
                return tuple(ast.literal_eval(node.value))
            except (ValueError, SyntaxError):
                return None
    return None


@register
class PlanContractRule(Rule):
    id = "plan-contract"
    description = ("every autotune kernel label and dist plan-shape "
                   "triple must have a committed planverify contract "
                   "(and no contract may be an orphan)")
    scope_prefixes = (REGISTRY_REL, DIST_REL, SPGEMM_REL)
    doc_inputs = (CONTRACTS_REL,)
    whole_program = True

    def triggers(self, rel: str) -> bool:
        return rel.startswith(CONTRACTS_REL) or super().triggers(rel)

    def check(self, ctx: Context, files: Sequence[str],
              kernel_labels=None, plan_shapes=None,
              contract_names=None) -> Iterable[Finding]:
        if kernel_labels is None:
            kernel_labels = registry_labels(ctx)
        if plan_shapes is None:
            plan_shapes = []
            for rel, name in ((DIST_REL, "DIST_PLAN_SHAPES"),
                              (SPGEMM_REL, "SPGEMM_PLAN_SHAPES")):
                shapes = plan_shape_literals(ctx, rel, name)
                if shapes is None:
                    yield Finding(
                        rule=self.id, path=rel, line=0,
                        message=f"{name} is missing or not a pure "
                                f"literal tuple in {rel} — planverify "
                                f"and this rule enumerate plan shapes "
                                f"from it")
                else:
                    plan_shapes.extend(shapes)
        if contract_names is None:
            contract_names = list_contracts()

        names = list(contract_names)
        claimed = set()

        for label in kernel_labels:
            prefix = kernel_prefix(label)
            hits = [n for n in names if n.startswith(prefix)]
            claimed.update(hits)
            if not hits:
                yield Finding(
                    rule=self.id, path=REGISTRY_REL, line=0,
                    message=f"kernel label {label!r} has no committed "
                            f"planverify contract "
                            f"({CONTRACTS_REL}{prefix}*.json) — "
                            f"{_UPDATE_HINT}")

        for triple in plan_shapes:
            prefix = dist_prefix(triple) + "-"
            hits = [n for n in names if n.startswith(prefix)]
            claimed.update(hits)
            if not hits:
                src = SPGEMM_REL if triple[0] == "dist_spgemm" \
                    else DIST_REL
                yield Finding(
                    rule=self.id, path=src, line=0,
                    message=f"plan shape {tuple(triple)!r} has no "
                            f"committed planverify contract "
                            f"({CONTRACTS_REL}{prefix}*.json) — "
                            f"{_UPDATE_HINT}")

        for name in sorted(set(names) - claimed):
            yield Finding(
                rule=self.id, path=CONTRACTS_REL + name, line=0,
                message=f"contract {name} matches no registry kernel "
                        f"label and no plan-shape triple — the "
                        f"program it contracted no longer exists; "
                        f"delete the file (or restore the plan shape)")

    def falsifiability(self, ctx: Context) -> List[Finding]:
        # Synthetic rot: a registered label with no contract file.
        probe = "zz-lint-falsifiability-probe"
        return list(self.check(
            ctx, [], kernel_labels=registry_labels(ctx) + [probe]))
