# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""settings-epoch: every settings attribute is epoch-bumping or
explicitly epoch-exempt, and nothing bypasses the epoch.

The plan cache (PR 4) keys compiled executables on ``settings.epoch``,
bumped by ``Settings.__setattr__`` for every post-init value change of
a lowering-relevant attribute; ``_EPOCH_EXEMPT`` names the attributes
whose mutation must NOT void ``warmup()`` guarantees.  That contract
has three rot modes, all checked here:

1. **stale exemption** — a name in ``_EPOCH_EXEMPT`` that no longer
   exists as a ``Settings`` attribute or property exempts nothing and
   hides a future re-use of the name from the epoch;
2. **epoch bypass** — package code writing
   ``settings.__dict__[...]``, ``vars(settings)[...]`` or
   ``object.__setattr__(settings, ...)`` skips ``__setattr__``
   entirely, mutating a knob without invalidating cached plans;
3. **unknown attribute** — a ``settings.<name>`` (or aliased
   ``_settings.<name>``) access for a name never assigned in
   ``Settings.__init__`` nor defined as a property: a typo'd knob read
   that would surface only as an ``AttributeError`` on a rarely-taken
   path.

``settings.py`` itself is exempt from (2) — ``__setattr__``'s
``self.__dict__`` bookkeeping IS the epoch mechanism.
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence, Set, Tuple

from ..core import Context, Finding, PKG_PREFIX, Rule, register

SETTINGS_PATH = "legate_sparse_tpu/settings.py"
# Receiver names treated as the settings singleton across the package.
RECEIVERS = frozenset({"settings", "_settings"})
# Internal bookkeeping attrs, always legal.
INTERNAL = frozenset({"_epoch", "_init_done"})


def settings_surface(ctx: Context, settings_rel: str = SETTINGS_PATH
                     ) -> Tuple[Set[str], Set[str], int]:
    """(declared attrs+properties, exempt names, exempt lineno) parsed
    from the Settings class."""
    tree = ctx.tree(settings_rel)
    attrs: Set[str] = set()
    exempt: Set[str] = set()
    exempt_line = 1
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Settings":
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            attrs.add(t.attr)
                if isinstance(sub, ast.FunctionDef) and \
                        sub.decorator_list:
                    for dec in sub.decorator_list:
                        if (isinstance(dec, ast.Name) and
                                dec.id == "property") or \
                           (isinstance(dec, ast.Attribute) and
                                dec.attr == "setter"):
                            attrs.add(sub.name)
            for stmt in node.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and \
                                t.id == "_EPOCH_EXEMPT":
                            exempt_line = stmt.lineno
                            for e in ast.walk(stmt.value):
                                if isinstance(e, ast.Constant) and \
                                        isinstance(e.value, str):
                                    exempt.add(e.value)
    attrs.add("epoch")
    return attrs, exempt, exempt_line


def _is_settings_receiver(node: ast.AST) -> bool:
    return isinstance(node, ast.Name) and node.id in RECEIVERS


@register
class SettingsEpochRule(Rule):
    id = "settings-epoch"
    description = ("settings attributes must be epoch-bumping or in "
                   "_EPOCH_EXEMPT; no __dict__/object.__setattr__ "
                   "bypasses; no unknown settings.<attr> accesses")
    scope_prefixes = (PKG_PREFIX,)
    whole_program = False
    bad_fixture = "tools/lint/fixtures/settings_epoch_bad.py"

    def check(self, ctx: Context, files: Sequence[str],
              settings_rel: str = SETTINGS_PATH) -> Iterable[Finding]:
        attrs, exempt, exempt_line = settings_surface(ctx, settings_rel)

        # (1) stale exemptions — attributed to settings.py, so only
        # emitted when it is in the scanned set.
        if settings_rel in files:
            for name in sorted(exempt - attrs - INTERNAL):
                yield Finding(
                    rule="settings-epoch", path=settings_rel,
                    line=exempt_line,
                    message=(f"_EPOCH_EXEMPT entry {name!r} is not a "
                             f"Settings attribute or property — stale "
                             f"exemption"))

        for rel in files:
            tree = ctx.tree(rel)
            in_settings = rel == settings_rel
            for node in ast.walk(tree):
                # (2) epoch bypasses
                if not in_settings and isinstance(node, ast.Attribute) \
                        and node.attr == "__dict__" \
                        and _is_settings_receiver(node.value):
                    yield Finding(
                        rule="settings-epoch", path=rel,
                        line=node.lineno,
                        message=("settings.__dict__ access bypasses "
                                 "Settings.__setattr__ — the epoch "
                                 "never bumps"))
                    continue
                if not in_settings and isinstance(node, ast.Call):
                    callee = node.func
                    if isinstance(callee, ast.Attribute) and \
                            callee.attr == "__setattr__" and \
                            isinstance(callee.value, ast.Name) and \
                            callee.value.id == "object" and node.args \
                            and _is_settings_receiver(node.args[0]):
                        yield Finding(
                            rule="settings-epoch", path=rel,
                            line=node.lineno,
                            message=("object.__setattr__(settings, "
                                     "...) bypasses the settings "
                                     "epoch"))
                        continue
                    if isinstance(callee, ast.Name) and \
                            callee.id == "vars" and node.args and \
                            _is_settings_receiver(node.args[0]):
                        yield Finding(
                            rule="settings-epoch", path=rel,
                            line=node.lineno,
                            message=("vars(settings) exposes the raw "
                                     "__dict__ — writes through it "
                                     "bypass the settings epoch"))
                        continue
                # (3) unknown attributes
                if isinstance(node, ast.Attribute) and \
                        _is_settings_receiver(node.value) and \
                        not node.attr.startswith("__") and \
                        node.attr not in attrs and \
                        node.attr not in INTERNAL:
                    yield Finding(
                        rule="settings-epoch", path=rel,
                        line=node.lineno,
                        message=(f"settings.{node.attr} is not a "
                                 f"declared Settings attribute or "
                                 f"property (typo'd knob?)"))
