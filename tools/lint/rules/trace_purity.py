# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""trace-purity: host-sync constructs must stay out of traced code.

The repo's performance contract (PR 2's sync-free Krylov, PR 5's
trace-suppressed fault injection) hinges on traced programs never
touching the host: a ``.item()``, a ``float()`` coercion of a traced
value, an ``np.asarray`` materialization, a ``time.*`` read or a
``print`` inside a jitted/``shard_map``-ped function or a
``lax.while_loop``/``lax.scan`` body either bakes a transfer into
every execution or (at best) runs at trace time and silently freezes a
value into the compiled program.

Detection: a function body counts as **traced** when its ``def`` is

- decorated with ``jit`` (``@jax.jit``, ``@partial(jax.jit, ...)``),
  or
- passed by name (or as a lambda) to a call in the closed
  ``TRACING_ENTRY_POINTS`` set — ``jit`` / ``maybe_jit`` /
  ``shard_map`` / ``lax.while_loop`` / ``lax.scan`` /
  ``lax.fori_loop`` / ``lax.cond`` / ``lax.switch``.

Inside traced bodies (nested defs included) the rule flags the closed
``HOST_SYNC`` construct set below.  ``float()``/``bool()``/``int()``
coercions are flagged only when the argument is a bare parameter name
of a function in the traced region — shape arithmetic on static ints
(``int(np.ceil(...))``) is trace-legal and common in the kernels, and
flagging it would drown the signal.

The escape hatches are the standard ones: a closed ``ALLOWED_CALLS``
set for dotted callees that look like violations but are host-legal in
this codebase, and inline ``# lint: disable=trace-purity`` with a
justification for deliberate trace-time work (e.g. a static probe that
runs once at trace time by design).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence, Set

from ..core import Context, Finding, Rule, register

# Callees whose function-valued arguments are traced.  Matched by the
# final name segment (``lax.while_loop`` and a bare ``while_loop``
# import both hit ``while_loop``).
TRACING_ENTRY_POINTS = frozenset({
    "jit", "maybe_jit", "shard_map", "while_loop", "scan",
    "fori_loop", "cond", "switch",
})

# Dotted callees that pattern-match a violation but are host-legal in
# this codebase (closed allowlist — extend with a comment saying why).
ALLOWED_CALLS: frozenset = frozenset()

# module.attr calls flagged inside traced bodies.
_NP_MATERIALIZERS = frozenset({
    "asarray", "array", "ascontiguousarray", "copy", "frombuffer",
    "fromiter", "save", "savez", "load",
})
_TIME_CALLS = frozenset({
    "time", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "sleep",
})
# obj.method() calls flagged anywhere in a traced body.
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})


def _callee_dotted(func: ast.AST) -> str:
    """Best-effort dotted name of a call's callee ('' when dynamic)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def _decorator_is_jit(dec: ast.AST) -> bool:
    """True for @jit/@jax.jit and @partial(jax.jit, ...) shapes: any
    name segment 'jit' anywhere in the decorator expression."""
    for node in ast.walk(dec):
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
    return False


def _tracing_call_targets(tree: ast.AST):
    """(names, lambdas): function names / lambda nodes passed to a
    tracing entry point anywhere in the module."""
    names: Set[str] = set()
    lambdas: List[ast.Lambda] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_dotted(node.func)
        if not callee or callee.split(".")[-1] not in \
                TRACING_ENTRY_POINTS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif isinstance(arg, ast.Lambda):
                lambdas.append(arg)
    return names, lambdas


def _traced_regions(tree: ast.AST):
    """Root nodes (defs / lambdas) whose bodies execute under trace."""
    names, regions = _tracing_call_targets(tree)
    regions = list(regions)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in names or any(
                    _decorator_is_jit(d) for d in node.decorator_list):
                regions.append(node)
    return regions


def _region_params(region: ast.AST) -> Set[str]:
    """Parameter names of every def/lambda inside the region."""
    params: Set[str] = set()
    for node in ast.walk(region):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                params.add(arg.arg)
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
    return params


def _check_region(region: ast.AST, rel: str, owner: str
                  ) -> Iterable[Finding]:
    params = _region_params(region)
    if isinstance(region, ast.Lambda):
        nodes = list(ast.walk(region.body))
    else:
        nodes = []
        for stmt in region.body:
            nodes.extend(ast.walk(stmt))
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_dotted(node.func)
        if callee in ALLOWED_CALLS:
            continue
        msg = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS:
            msg = (f".{node.func.attr}() forces a device->host sync")
        elif callee.startswith(("np.", "numpy.")) and \
                callee.split(".")[-1] in _NP_MATERIALIZERS:
            msg = (f"{callee}() materializes a traced value on the "
                   f"host (or freezes a trace-time constant)")
        elif callee.startswith("time.") and \
                callee.split(".")[-1] in _TIME_CALLS:
            msg = (f"{callee}() reads the host clock at trace time — "
                   f"it will not re-run per execution")
        elif callee in ("jax.device_get", "device_get"):
            msg = f"{callee}() forces a device->host transfer"
        elif callee == "print":
            msg = ("print() inside traced code runs at trace time "
                   "only (use jax.debug.print)")
        elif callee in ("float", "bool", "int") and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in params:
            msg = (f"{callee}({node.args[0].id}) coerces a traced "
                   f"argument to a host scalar")
        if msg:
            yield Finding(
                rule="trace-purity", path=rel, line=node.lineno,
                message=f"in traced {owner}: {msg}")


@register
class TracePurityRule(Rule):
    id = "trace-purity"
    description = ("host-sync constructs (.item(), float()/bool() "
                   "coercions, np.* materialization, time.*, print) "
                   "inside jit/shard_map/while_loop/scan bodies")
    bad_fixture = "tools/lint/fixtures/trace_purity_bad.py"

    def check(self, ctx: Context, files: Sequence[str]
              ) -> Iterable[Finding]:
        for rel in files:
            tree = ctx.tree(rel)
            seen = set()
            for region in _traced_regions(tree):
                key = id(region)
                if key in seen:
                    continue
                seen.add(key)
                owner = getattr(region, "name", "<lambda>")
                yield from _check_region(region, rel, owner)
