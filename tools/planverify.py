#!/usr/bin/env python3
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Entry shim for planverify (``tools/verify/``): pins the 8-device
virtual CPU mesh BEFORE jax initializes (contract programs lower
against the same topology the test suite uses — see tests/conftest.py)
then dispatches to the package CLI.

Usage: ``python tools/planverify.py [--changed] [--json] [ids...]``
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from legate_sparse_tpu._platform import pin_cpu  # noqa: E402

from tools.verify import catalog  # noqa: E402

pin_cpu(catalog.MESH_DEVICES, override_env=False)

from tools.verify.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
