#!/bin/bash
# One-shot round-3 on-chip capture: run the moment the tunnel answers.
# Ordered most-important-first so a short tunnel window still records
# the headline evidence (VERDICT r2 items 1, 3, 6, 7).
#
#   bash tools/round3_capture.sh
#
# Appends everything to TPU_EVIDENCE.md (via the python tools) and
# captures bench/pde/sweep output under evidence/ for the record.
set -u
cd "$(dirname "$0")/.."
mkdir -p evidence
stamp=$(date -u +"%Y-%m-%dT%H:%M:%SZ")

probe() {
  timeout 90 python -c "from legate_sparse_tpu._platform import ACCEL_PROBE_CODE as c; exec(c)" >/dev/null 2>&1
}

if ! probe; then
  echo "$stamp: TPU unreachable; aborting capture" | tee -a evidence/round3_capture.log
  exit 1
fi
echo "$stamp: TPU alive; capturing" | tee -a evidence/round3_capture.log

# 1. The full evidence sweep: bench.py (BENCH-contract metrics incl.
#    spgemm/gmg/bsr), -m tpu lane, kernel shoot-out, CG 2048^2.
timeout 5400 python tools/tpu_capture.py 2>&1 | tail -3 | tee -a evidence/round3_capture.log

# 2. Irregular-path shoot-out (XLA ELL vs BSR across densities).
timeout 3600 python tools/tune_irregular.py 2>&1 | tail -2 | tee -a evidence/round3_capture.log

# 3. BASELINE config 3: pde.py at 4096^2 on the single chip.
timeout 3600 python examples/pde.py -n 4096 -m 4096 -i 300 \
  > evidence/pde_4096.txt 2>&1
tail -3 evidence/pde_4096.txt | tee -a evidence/round3_capture.log

# 4. BASELINE config 2 shape: SpMV sweep to 1e7+ rows.
timeout 3600 python examples/spmv_microbenchmark.py \
  --nmin 1m --nmax 16m -i 25 > evidence/spmv_sweep.txt 2>&1
tail -6 evidence/spmv_sweep.txt | tee -a evidence/round3_capture.log

echo "done: see TPU_EVIDENCE.md + evidence/" | tee -a evidence/round3_capture.log
