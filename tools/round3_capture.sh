#!/bin/bash
# One-shot round-3 on-chip capture: run the moment the tunnel answers.
# Ordered most-important-first so a short tunnel window still records
# the headline evidence (VERDICT r2 items 1, 3, 6, 7).  Every phase
# inside tools/tpu_capture.py appends to TPU_EVIDENCE.md as it
# finishes — the 2026-07-31 monolithic attempt lost 90 min of on-chip
# data to an outer timeout, so nothing here buffers results.
#
#   bash tools/round3_capture.sh
set -u
cd "$(dirname "$0")/.."
mkdir -p evidence
stamp=$(date -u +"%Y-%m-%dT%H:%M:%SZ")

probe() {
  timeout 90 python -c "from legate_sparse_tpu._platform import ACCEL_PROBE_CODE as c; exec(c)" >/dev/null 2>&1
}

if ! probe; then
  echo "$stamp: TPU unreachable; aborting capture" | tee -a evidence/round3_capture.log
  exit 1
fi
echo "$stamp: TPU alive; capturing" | tee -a evidence/round3_capture.log
start_lines=$(wc -l < TPU_EVIDENCE.md 2>/dev/null || echo 0)

# 0. QUICK fault isolation first (2 sizes x 2 modes, bounded well
#    below a window length): the 11:24 window showed the production
#    Pallas DIA path crashes the TPU worker at the bench size; each
#    probe runs in its own subprocess and appends its verdict
#    immediately, so the crashing configuration is named even if the
#    window closes right after — without consuming the window the way
#    a full sweep would.
timeout 1800 python tools/fault_isolate.py --quick 2>&1 | tee -a evidence/round3_capture.log

# 1. The headline evidence sweep, incremental appends: tunnel probe,
#    bench.py (canary-guarded: falls back to the XLA band path when the
#    Pallas kernel faults the worker), kernel shoot-out, -m tpu lane,
#    SpGEMM, CG 2048^2.
timeout 9600 python tools/tpu_capture.py 2>&1 | tee -a evidence/round3_capture.log

# 1b. Full-size fault isolation after the headline data is banked.
timeout 4200 python tools/fault_isolate.py 2>&1 | tee -a evidence/round3_capture.log

# 2. Irregular-path shoot-out (XLA ELL vs BSR across densities).
#    Inner timeout 3000 < outer 3600 so the inner result write wins.
LEGATE_SPARSE_TPU_SHOOTOUT_TIMEOUT=3000 \
timeout 3600 python tools/tune_irregular.py 2>&1 | tail -2 | tee -a evidence/round3_capture.log

# 3. BASELINE config 3: pde.py at 4096^2 on the single chip.
timeout 3600 python examples/pde.py -n 4096 -m 4096 -i 300 \
  > evidence/pde_4096.txt 2>&1
tail -3 evidence/pde_4096.txt | tee -a evidence/round3_capture.log

# 4. BASELINE config 2 shape: SpMV sweep to 1e7+ rows.
timeout 3600 python examples/spmv_microbenchmark.py \
  --nmin 1m --nmax 16m -i 25 > evidence/spmv_sweep.txt 2>&1
tail -6 evidence/spmv_sweep.txt | tee -a evidence/round3_capture.log

echo "done: see TPU_EVIDENCE.md + evidence/" | tee -a evidence/round3_capture.log

# Success (exit 0) only if this run actually recorded on-chip data —
# the watcher's one-shot "done" marker keys off this, so a run the
# tunnel killed mid-way is retried on the next window.
tail -n +$((start_lines + 1)) TPU_EVIDENCE.md | grep -q '"platform": "tpu"'
