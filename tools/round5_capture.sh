#!/bin/bash
# One-shot round-5 on-chip capture: fired by tools/tunnel_watch.sh the
# moment the tunnel answers.  Ordered most-important-first so a short
# window still records the headline evidence (VERDICT r4 item 1):
#
#   1. QUICK fault isolation   — names the crashing banded config
#                                (r3: production kernel faulted the
#                                worker while eager launches passed)
#   2. tools/tpu_capture.py    — bench.py (canary ladder picks the
#                                fastest SURVIVING band variant:
#                                pallas -> pallas-jroll -> xla; emits
#                                vs_baseline + bsr_gbs), kernel
#                                shoot-out, -m tpu lane, SpGEMM, CG
#   3. irregular shoot-out     — XLA ELL vs BSR across densities
#   4. FULL fault isolation    — size x lowering grid for the record
#   5. pde 4096^2 + 16M SpMV   — BASELINE configs 2-3 scale demos
#
# Every phase appends to TPU_EVIDENCE.md the moment it finishes
# (fsync'd); nothing buffers results.  Phase budgets are sized from
# the MEASURED tunnel (scalar fetch ~1 s, upload 6-19 MB/s, compiles
# 20-60 s each): phases 1+2 worst-case fit a 90-minute window.
#
#   bash tools/round5_capture.sh
set -u
cd "$(dirname "$0")/.."
mkdir -p evidence
stamp=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
log=evidence/round5_capture.log

probe() {
  timeout 90 python -c "from legate_sparse_tpu._platform import ACCEL_PROBE_CODE as c; exec(c)" >/dev/null 2>&1
}

if ! probe; then
  echo "$stamp: TPU unreachable; aborting capture" | tee -a "$log"
  exit 1
fi
echo "$stamp: TPU alive; capturing" | tee -a "$log"
start_lines=$(wc -l < TPU_EVIDENCE.md 2>/dev/null || echo 0)

# 1. Quick isolation: one 2^22 pallas probe (+ jroll only on failure),
#    each in its own subprocess with immediate appends.
timeout 900 python tools/fault_isolate.py --quick 2>&1 | tee -a "$log"

# 2. Headline sweep (bench with the variant-selection canary ladder,
#    kernel shoot-out, tpu test lane, SpGEMM, CG) — incremental appends.
#    Drop any stale variant selection from a previous run first: if
#    THIS run's bench never reaches the ladder, later phases must not
#    inherit an outdated pin.
rm -f evidence/band_variant.env
timeout 8400 python tools/tpu_capture.py 2>&1 | tee -a "$log"

# Later phases run the band variant bench's canary ladder proved out
# (separate processes: the selection does not propagate by itself).
if [ -f evidence/band_variant.env ]; then
  # shellcheck disable=SC1091
  . evidence/band_variant.env
  echo "using band variant env: $(cat evidence/band_variant.env | tail -n +2)" | tee -a "$log"
fi

# 3. Irregular-path shoot-out (XLA ELL vs BSR across densities).
LEGATE_SPARSE_TPU_SHOOTOUT_TIMEOUT=1500 \
timeout 1800 python tools/tune_irregular.py 2>&1 | tail -2 | tee -a "$log"

# 4. Full-grid fault isolation after the headline data is banked
#    (worst case 4440s of probe budgets + recovery pauses < 5400).
timeout 5400 python tools/fault_isolate.py 2>&1 | tee -a "$log"

# 5. Scale demos (BASELINE configs 2-3).
timeout 1800 python examples/pde.py -n 4096 -m 4096 -i 300 \
  > evidence/pde_4096.txt 2>&1
tail -3 evidence/pde_4096.txt | tee -a "$log"

timeout 1800 python examples/spmv_microbenchmark.py \
  --nmin 1m --nmax 16m -i 25 > evidence/spmv_sweep.txt 2>&1
tail -6 evidence/spmv_sweep.txt | tee -a "$log"

echo "done: see TPU_EVIDENCE.md + evidence/" | tee -a "$log"

# Success (exit 0) only if this run actually recorded on-chip data —
# the watcher's one-shot "done" marker keys off this, so a run the
# tunnel killed mid-way is retried on the next window.
tail -n +$((start_lines + 1)) TPU_EVIDENCE.md | grep -q '"platform": "tpu"'
