#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""sparselint — unified AST static-analysis suite (entry shim).

The framework lives in ``tools/lint/`` (rule registry, inline
``# lint: disable=<rule>`` suppressions, committed baseline,
falsifiability fixtures); this file exists so the CLI is invocable the
same way as the repo's other tools::

    python tools/sparselint.py                 # full scan, exit 0/1
    python tools/sparselint.py --changed       # only git-touched files
    python tools/sparselint.py --json          # findings artifact
    python tools/sparselint.py --list-rules    # rule catalog
    python tools/sparselint.py --update-baseline

Rule catalog, suppression syntax and the baseline workflow:
``docs/LINT.md``.
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
