# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""One-shot TPU evidence capture: run when the chip is reachable.

Probes the accelerator (bounded subprocess), then records in sequence:
1. bench.py JSON line (the driver-contract metric),
2. the @pytest.mark.tpu smoke lane,
3. Pallas ELL kernel lowering check + timing vs the XLA paths,
4. CG ms/iter on the pde operator (2048^2 grid, f32).

Appends everything to TPU_EVIDENCE.md with a timestamp so perf claims
in the repo are backed by recorded runs.

Usage: python tools/tpu_capture.py  (from the repo root)
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "TPU_EVIDENCE.md")


def probe(timeout_s: int = 90) -> bool:
    code = ("import jax; ds = jax.devices(); "
            "assert ds and ds[0].platform != 'cpu', ds; print('ok')")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True, cwd=ROOT)
        return r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def run(cmd, timeout_s):
    try:
        r = subprocess.run(cmd, timeout=timeout_s, capture_output=True,
                           text=True, cwd=ROOT)
        return r.returncode, r.stdout[-4000:], r.stderr[-2000:]
    except subprocess.TimeoutExpired:
        return 124, "", "timeout"


KERNEL_TIMING = r"""
import time, json
import numpy as np, jax, jax.numpy as jnp
import legate_sparse_tpu as sparse
from legate_sparse_tpu.ops import spmv as spmv_ops

def t(fn, *a, iters=20, warm=3):
    for _ in range(warm):
        out = fn(*a)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*a)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters

n, W = 1 << 22, 11
half = W // 2
offs = list(range(-half, half + 1))
diags = [np.ones(n - abs(o), dtype=np.float32) for o in offs]
A = sparse.diags(diags, offs, shape=(n, n), format="csr", dtype=np.float32)
x = jnp.ones((n,), jnp.float32)
res = {"n": n, "W": W, "platform": jax.devices()[0].platform}
res["dia_ms"] = round(t(lambda: A @ x) * 1e3, 3)
ell = A._get_ell()
if ell is None:
    from legate_sparse_tpu.ops.spmv import ell_pack_device
    ell = ell_pack_device(A.data, A.indices, A.indptr, n, W)
res["ell_xla_ms"] = round(t(spmv_ops.ell_spmv, ell[0], ell[1], ell[2], x) * 1e3, 3)
try:
    from legate_sparse_tpu.ops.pallas_spmv import pallas_ell_spmv
    res["ell_pallas_ms"] = round(t(pallas_ell_spmv, ell[0], ell[1], ell[2], x) * 1e3, 3)
except Exception as e:
    res["ell_pallas_error"] = repr(e)[:200]
print(json.dumps(res))
"""

CG_TIMING = r"""
import time, json
import numpy as np, jax
import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg

N = 2048
n = N * N
main = np.full(n, 4.0, np.float32)
off1 = np.full(n - 1, -1.0, np.float32)
off1[np.arange(1, N) * N - 1] = 0.0
offn = np.full(n - N, -1.0, np.float32)
A = sparse.diags([main, off1, off1, offn, offn], [0, 1, -1, N, -N],
                 shape=(n, n), format="csr", dtype=np.float32)
b = np.ones(n, np.float32)
x, it = linalg.cg(A, b, rtol=1e-6, maxiter=50)   # warmup + compile
jax.block_until_ready(x)
t0 = time.perf_counter()
x, it = linalg.cg(A, b, rtol=0.0, maxiter=200)
jax.block_until_ready(x)
dt = time.perf_counter() - t0
print(json.dumps({"grid": f"{N}x{N}", "rows": n,
                  "cg_ms_per_iter": round(dt / int(it) * 1e3, 4),
                  "iters": int(it),
                  "platform": jax.devices()[0].platform}))
"""


def main() -> None:
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    if not probe():
        print(f"{stamp}: TPU unreachable; nothing recorded")
        sys.exit(1)
    lines = [f"\n## Capture {stamp}\n"]

    rc, out, err = run([sys.executable, "bench.py"], 900)
    lines.append(f"### bench.py (rc={rc})\n```json\n{out.strip()}\n```\n")
    if rc != 0:
        lines.append(f"stderr: `{err[-500:]}`\n")

    rc, out, err = run(
        [sys.executable, "-m", "pytest", "-m", "tpu", "tests/", "-q"], 900
    )
    tail = "\n".join(out.strip().splitlines()[-3:])
    lines.append(f"### tpu smoke lane (rc={rc})\n```\n{tail}\n```\n")
    if rc != 0:
        lines.append(f"stderr: `{err[-500:]}`\n")

    rc, out, err = run([sys.executable, "-c", KERNEL_TIMING], 900)
    lines.append(f"### kernel timings (rc={rc})\n```json\n{out.strip()}\n```\n")
    if rc != 0:
        lines.append(f"stderr: `{err[-500:]}`\n")

    rc, out, err = run([sys.executable, "-c", CG_TIMING], 900)
    lines.append(f"### CG pde 2048^2 f32 (rc={rc})\n```json\n{out.strip()}\n```\n")
    if rc != 0:
        lines.append(f"stderr: `{err[-500:]}`\n")

    header = "" if os.path.exists(OUT) else (
        "# TPU evidence log\n\nRecorded runs on the real chip backing "
        "the perf claims in README.md / code comments.\n"
    )
    with open(OUT, "a") as f:
        f.write(header + "".join(lines))
    print(f"recorded -> {OUT}")


if __name__ == "__main__":
    main()
