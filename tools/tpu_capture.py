# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""One-shot TPU evidence capture: run when the chip is reachable.

Probes the accelerator (bounded subprocess, one real op round trip),
then records in sequence (most-important-first, so a tunnel drop or
timeout mid-run still keeps everything already measured):
1. bench.py JSON line (the driver-contract metric),
2. SpMV kernel shoot-out: Pallas DIA vs XLA DIA vs XLA ELL,
   loop-delta timed (block_until_ready lies on this tunnel — see
   ``legate_sparse_tpu/bench_timing.py``),
3. the @pytest.mark.tpu smoke lane ON the chip
   (LEGATE_SPARSE_TPU_TEST_PLATFORM=tpu),
4. SpGEMM end-to-end,
5. CG ms/iter on the pde operator (2048^2 grid, f32).

Every phase's result is APPENDED TO TPU_EVIDENCE.md THE MOMENT IT
FINISHES (the first capture attempt on 2026-07-31 buffered all phases
in memory and lost 90 minutes of on-chip data to the outer timeout),
with per-phase wall times so slow-tunnel behavior is itself recorded.

Usage: python tools/tpu_capture.py  (from the repo root)
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "TPU_EVIDENCE.md")


def probe(timeout_s: int = 90) -> bool:
    # The probe snippet is resolved in the CHILD so this parent stays
    # jax-free (a wedged TPU runtime must only ever hang a bounded
    # subprocess, never the capture tool itself).
    code = ("from legate_sparse_tpu._platform import ACCEL_PROBE_CODE "
            "as c; exec(c)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True, cwd=ROOT)
        return r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def append(text: str) -> None:
    if not os.path.exists(OUT):
        text = ("# TPU evidence log\n\nRecorded runs on the real chip "
                "backing the perf claims in README.md / code comments.\n"
                + text)
    with open(OUT, "a") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())


def load_band_variant(not_before: float = 0.0) -> dict:
    """Env of the band variant bench's canary ladder proved out
    (bench._persist_variant).  Later phases run that variant instead
    of a possibly-faulting default: the r3 worker stayed WEDGED after
    a fault, so one bad phase can cost the rest of the window.

    ``not_before``: ignore a file older than this timestamp — a pin
    left by a PREVIOUS capture run must not leak into this one when
    the bench phase died before re-selecting (round4_capture.sh also
    removes the file up front; this guards standalone invocations)."""
    path = os.path.join(ROOT, "evidence", "band_variant.env")
    env = {}
    try:
        if os.path.getmtime(path) < not_before:
            return env
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("export ") and "=" in line:
                    k, _, v = line[len("export "):].partition("=")
                    env[k.strip()] = v.strip()
    except OSError:
        pass
    return env


def run_phase(title: str, cmd, timeout_s, env_extra=None,
              tail_lines: int | None = None) -> int:
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    t0 = time.perf_counter()
    try:
        r = subprocess.run(cmd, timeout=timeout_s, capture_output=True,
                           text=True, cwd=ROOT, env=env)
        rc, out, err = r.returncode, r.stdout[-4000:], r.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        # Keep whatever the phase printed before the timeout — phases
        # print partial JSON mid-script for exactly this case.
        def _txt(b):
            if b is None:
                return ""
            return b.decode("utf-8", "replace") if isinstance(b, bytes) else b
        rc = 124
        out = _txt(e.stdout)[-4000:]
        err = (_txt(e.stderr)[-1500:] + f"\ntimeout after {timeout_s}s")
    dt = time.perf_counter() - t0
    body = out.strip()
    if tail_lines is not None:
        body = "\n".join(body.splitlines()[-tail_lines:])
    block = (f"### {title} (rc={rc}, wall={dt:.0f}s)\n"
             f"```json\n{body}\n```\n")
    if rc != 0:
        block += f"stderr: `{err[-600:]}`\n"
    append(block)
    print(f"{title}: rc={rc} wall={dt:.0f}s", flush=True)
    return rc


# Tunnel characterization: upload bandwidth + dispatch/fetch latency,
# so phase budgets below are explainable from first principles.
TUNNEL_PROBE = r"""
import json, time
import numpy as np, jax, jax.numpy as jnp
res = {"platform": jax.devices()[0].platform}
z = jnp.zeros((8, 128)); float(z.sum())  # backend warm
t0 = time.perf_counter(); float(jnp.ones((1,)).sum())
res["scalar_fetch_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
for mb in (16, 64):
    a = np.ones((mb << 20) // 4, np.float32)
    t0 = time.perf_counter()
    d = jax.device_put(a); float(d[-1])
    dt = time.perf_counter() - t0
    res[f"upload_{mb}mb_mbps"] = round(mb / dt, 1)
print(json.dumps(res))
"""

KERNEL_TIMING = r"""
import json, os
import numpy as np, jax, jax.numpy as jnp
import legate_sparse_tpu as sparse
from legate_sparse_tpu.bench_timing import loop_ms_per_iter
from legate_sparse_tpu.ops import spmv as spmv_ops
from legate_sparse_tpu.ops import dia_ops, pallas_dia

n, W = 1 << 22, 11
half = W // 2
offs = list(range(-half, half + 1))
val = np.float32(1.0 / W)
diags = [np.full(n - abs(o), val, dtype=np.float32) for o in offs]
A = sparse.diags(diags, offs, shape=(n, n), format="csr", dtype=np.float32)
x = jnp.ones((n,), jnp.float32)
res = {"n": n, "W": W, "platform": jax.devices()[0].platform,
       "x64": bool(jax.config.jax_enable_x64)}
bytes_dia = (W + 2) * 4 * n

dia = A._get_dia()
dd, offsets, mask = dia
res["band_masked"] = mask is not None

packed = pallas_dia.pack_band(dd, offsets, A.shape, mask=mask)
if packed is not None:
    ms = loop_ms_per_iter(
        lambda v: pallas_dia.pallas_dia_spmv(
            packed.rdata, packed.rmask, v, packed.offsets, packed.shape,
            packed.tile),
        x, k_lo=5, k_hi=35)
    res["pallas_dia_ms"] = round(ms, 4)
    res["pallas_dia_gbs"] = round(bytes_dia / ms / 1e6, 1)
else:
    res["pallas_dia_ms"] = None

# The shipped XLA fallback is the FUSED pad+slice form (what csr.dot
# runs when the Pallas kernel is unavailable), not the old at[].add
# chain.
dpad, mpad = A._get_dia_fused()
step = lambda v: dia_ops.dia_spmv_fused(dpad, mpad, v, offsets, A.shape)
ms = loop_ms_per_iter(step, x, k_lo=3, k_hi=13)
res["xla_dia_fused_ms"] = round(ms, 4)
res["xla_dia_fused_gbs"] = round(bytes_dia / ms / 1e6, 1)

ell = A._get_ell()
if ell is None:
    ell = spmv_ops.ell_pack_device(A.data, A.indices, A.indptr, n, W)
ms = loop_ms_per_iter(
    lambda v: spmv_ops.ell_spmv(ell[0], ell[1], ell[2], v) * np.float32(1.0),
    x, k_lo=2, k_hi=6)
res["xla_ell_ms"] = round(ms, 4)
print(json.dumps(res), flush=True)   # bank before the tile sweep

# Pallas tile sweep: the grid length scales inversely with the tile
# (fault diagnosis) and the tile sets the VMEM working set (tuning).
if packed is not None:
    for tl in (8192, 32768, 131072):
        os.environ["LEGATE_SPARSE_TPU_PALLAS_TILE"] = str(tl)
        try:
            pk = pallas_dia.pack_band(dd, offsets, A.shape, mask=mask)
            if pk is None or pk.tile != tl:
                res[f"pallas_tile_{tl}"] = None
                continue
            ms = loop_ms_per_iter(
                lambda v, pk=pk: pallas_dia.pallas_dia_spmv(
                    pk.rdata, pk.rmask, v, pk.offsets, pk.shape,
                    pk.tile),
                x, k_lo=5, k_hi=35)
            res[f"pallas_tile_{tl}"] = round(bytes_dia / ms / 1e6, 1)
        except Exception as e:
            res[f"pallas_tile_{tl}"] = f"err:{e!r:.80}"
        finally:
            os.environ.pop("LEGATE_SPARSE_TPU_PALLAS_TILE", None)
        print(json.dumps(res), flush=True)
print(json.dumps(res))
"""

SPGEMM_TIMING = r"""
import time, json
import numpy as np, jax, jax.numpy as jnp
import legate_sparse_tpu as sparse

res = {"platform": jax.devices()[0].platform}

def end_to_end_ms(f, reps=2):
    # SpGEMM is host-coupled (nnz size oracle blocks), so time the
    # whole user-visible call with a true result fetch; best-of-reps
    # after a warmup.  Includes ~one RPC round trip of fixed cost.
    f()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        C = f()
        _ = float(np.asarray(C.data[0]))
        best = min(best, time.perf_counter() - t0)
    return round(best * 1e3, 2)

n, W = 1 << 20, 11
half = W // 2
offs = list(range(-half, half + 1))
val = np.float32(1.0 / W)
diags = [np.full(n - abs(o), val, dtype=np.float32) for o in offs]
A = sparse.diags(diags, offs, shape=(n, n), format="csr", dtype=np.float32)
res["banded_n"] = n
res["banded_spgemm_ms"] = end_to_end_ms(lambda: A @ A)
print(json.dumps(res))

m = 1 << 17
rng = np.random.default_rng(0)
counts = rng.integers(1, 2 * W, size=m).astype(np.int64)
indptr = np.zeros(m + 1, np.int64); np.cumsum(counts, out=indptr[1:])
nnz = int(indptr[-1])
cols = rng.integers(0, m, size=nnz).astype(np.int32)
row_ids = np.repeat(np.arange(m), counts)
order = np.lexsort((cols, row_ids))
B = sparse.csr_array((np.ones(nnz, np.float32), cols[order], indptr),
                     shape=(m, m))
res["esc_n"] = m
res["esc_spgemm_ms"] = end_to_end_ms(lambda: B @ B)
print(json.dumps(res))
"""

CG_TIMING = r"""
import time, json
import numpy as np, jax
import legate_sparse_tpu as sparse
import legate_sparse_tpu.linalg as linalg

N = 2048
n = N * N
main = np.full(n, 4.0, np.float32)
off1 = np.full(n - 1, -1.0, np.float32)
off1[np.arange(1, N) * N - 1] = 0.0
offn = np.full(n - N, -1.0, np.float32)
A = sparse.diags([main, off1, off1, offn, offn], [0, 1, -1, N, -N],
                 shape=(n, n), format="csr", dtype=np.float32)
b = np.ones(n, np.float32)
def timed(maxiter):
    # warm (compile this maxiter variant), then best-of-2 with a host
    # fetch as the only trusted sync on this tunnel.
    best = float("inf")
    for rep in range(3):
        t0 = time.perf_counter()
        x, it = linalg.cg(A, b, rtol=0.0, maxiter=maxiter)
        _ = float(np.asarray(x[0]))
        if rep:
            best = min(best, time.perf_counter() - t0)
    return best

dt, dt2 = timed(100), timed(300)
if dt2 <= dt:
    print(json.dumps({"grid": f"{N}x{N}", "rows": n,
                      "error": "unresolvable timing",
                      "t100_s": round(dt, 4), "t300_s": round(dt2, 4)}))
else:
    per_iter = (dt2 - dt) / 200    # fixed dispatch+fetch cost cancels
    print(json.dumps({"grid": f"{N}x{N}", "rows": n,
                      "cg_ms_per_iter": round(per_iter * 1e3, 4),
                      "platform": jax.devices()[0].platform}))
"""


def main() -> None:
    t_start = time.time()
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    if not probe():
        print(f"{stamp}: TPU unreachable; nothing recorded")
        sys.exit(1)
    append(f"\n## Capture {stamp}\n")

    run_phase("tunnel characterization",
              [sys.executable, "-c", TUNNEL_PROBE], 600)

    # Budgets are derived from the measured tunnel characteristics
    # (scalar fetch ~1 s, uploads 6-19 MB/s, per-trip-count compiles
    # 20-60 s): bench = canary ladder (<= 2x480 s) + ~6 timed phases;
    # the kernel shoot-out needs ~3 loop compiles per formulation with
    # the adaptive trip-count selection (bench_timing r4) instead of
    # the blind escalation that blew the r3 1500 s budget.
    run_phase("bench.py", [sys.executable, "bench.py"], 2700)

    # Every later phase runs the surviving band variant (see
    # load_band_variant).  The DEFAULT formulation's own timings are
    # not lost: the full fault-isolation phase records eager and
    # looped numbers per mode at four sizes.
    variant_env = load_band_variant(not_before=t_start)
    if variant_env:
        append(f"(later phases use band variant env: {variant_env})\n")

    run_phase("kernel timings 2^22",
              [sys.executable, "-c", KERNEL_TIMING], 900,
              env_extra=variant_env)

    run_phase("tpu smoke lane",
              [sys.executable, "-m", "pytest", "-m", "tpu", "tests/",
               "-q", "--durations=10"],
              1500,
              env_extra={"LEGATE_SPARSE_TPU_TEST_PLATFORM": "tpu",
                         **variant_env},
              tail_lines=14)

    run_phase("SpGEMM end-to-end",
              [sys.executable, "-c", SPGEMM_TIMING], 900,
              env_extra=variant_env)

    run_phase("CG pde 2048^2 f32",
              [sys.executable, "-c", CG_TIMING], 900,
              env_extra=variant_env)

    print(f"recorded -> {OUT}")


if __name__ == "__main__":
    main()
