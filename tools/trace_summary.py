#!/usr/bin/env python
# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Pretty-print a legate_sparse_tpu trace file as a per-op table.

Reads either export format (Chrome-trace ``*.trace.json`` from
``bench.py`` / ``obs.write_chrome_trace``, or newline-JSON from
``obs.write_jsonl``) and renders the per-op aggregation: calls,
total/first-call/steady-state time, nnz and bytes totals, achieved
GB/s — and, given the measured stream roofline, the fraction of it
each op reaches.

Usage::

    python tools/trace_summary.py BENCH_20260804T120000.trace.json
    python tools/trace_summary.py run.trace.json --stream-gbs 819
    python tools/trace_summary.py run.trace.json --events --counters
    python tools/trace_summary.py run.trace.json --comm
    python tools/trace_summary.py run.trace.json --plans
    python tools/trace_summary.py run.trace.json --resil
    python tools/trace_summary.py run.trace.json --gateway
    python tools/trace_summary.py run.trace.json --tenants
    python tools/trace_summary.py run.trace.json --autotune
    python tools/trace_summary.py run.trace.json --flows --slo

``--stream-gbs`` defaults to the ``stream_gbs`` recorded in the trace
file's bench metadata when present (bench.py embeds its result blob).
Exit status: 2 when the file contains no span records (the same
"silent no-op wiring" condition bench.py guards against).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

from legate_sparse_tpu.obs import report  # noqa: E402


def render_comm_table(counters: dict) -> str:
    """Per-op x collective table from the ``comm.*`` ledger counters
    embedded in a Chrome-trace artifact: collective-op count and
    predicted interconnect bytes (obs/comm.py accounting convention:
    total across the mesh, counted once at each receiver)."""
    rows = {}
    by_layout = {}
    for name, val in counters.items():
        if not name.startswith("comm.") or name.startswith("comm.total"):
            continue
        body = name[len("comm."):]
        is_bytes = body.endswith("_bytes")
        if is_bytes:
            body = body[: -len("_bytes")]
        if body.startswith("layout."):
            # comm.layout.<layout>.<op>[_bytes] aggregates: grouped in
            # their own by-layout section, not the flat table (they
            # would double-count the per-collective rows).
            layout, _, op = body[len("layout."):].partition(".")
            row = by_layout.setdefault((layout, op),
                                       {"calls": 0, "bytes": 0})
            row["bytes" if is_bytes else "calls"] += val
            continue
        op, _, coll = body.rpartition(".")
        row = rows.setdefault((op, coll), {"calls": 0, "bytes": 0})
        row["bytes" if is_bytes else "calls"] += val
    if not rows:
        return "no comm.* counters recorded (no distributed ops ran?)"
    headers = ["op", "collective", "calls", "bytes", "MB"]
    lines = []
    for (op, coll), row in sorted(rows.items(),
                                  key=lambda kv: -kv[1]["bytes"]):
        lines.append([op, coll, str(int(row["calls"])),
                      str(int(row["bytes"])),
                      f"{row['bytes'] / 2**20:.3f}"])
    total_b = sum(r["bytes"] for r in rows.values())
    total_c = sum(r["calls"] for r in rows.values())
    lines.append(["TOTAL", "", str(int(total_c)), str(int(total_b)),
                  f"{total_b / 2**20:.3f}"])
    out = report.format_table(headers, lines, left_cols=2)
    if by_layout:
        lay_headers = ["layout", "op", "calls", "bytes", "MB"]
        lay_lines = []
        for (layout, op), row in sorted(by_layout.items(),
                                        key=lambda kv: -kv[1]["bytes"]):
            lay_lines.append([layout, op, str(int(row["calls"])),
                              str(int(row["bytes"])),
                              f"{row['bytes'] / 2**20:.3f}"])
        out += ("\n\nby layout (partition strategy):\n"
                + report.format_table(lay_headers, lay_lines,
                                      left_cols=2))
    return out


def render_autotune_table(counters: dict) -> str:
    """Routing/measurement ledger from the ``autotune.*`` counters
    embedded in a Chrome-trace artifact: verdict store activity, the
    route hit/miss/decline funnel, and per-kernel routed-dispatch
    counts (the dynamic ``autotune.route.<label>`` rows)."""
    rows = {name: val for name, val in counters.items()
            if name.startswith("autotune.")}
    if not rows:
        return ("no autotune.* counters recorded (autotuner off — "
                "LEGATE_SPARSE_TPU_AUTOTUNE unset?)")
    headers = ["counter", "value"]
    lines = [[name, str(int(val))] for name, val in sorted(rows.items())]
    return report.format_table(headers, lines, left_cols=1)


def render_graph_table(counters: dict) -> str:
    """Graph-analytics ledger from the ``graph.*`` counters embedded
    in a Chrome-trace artifact: per-algorithm runs/iteration totals
    and the per-semiring distributed dispatch counts
    (``graph.dist_spmv.<semiring>`` / ``graph.dist_spmm.<semiring>`` /
    ``graph.matvec.<semiring>`` rows)."""
    rows = {name: val for name, val in counters.items()
            if name.startswith("graph.")}
    if not rows:
        return ("no graph.* counters recorded (no "
                "legate_sparse_tpu.graph algorithm or semiring "
                "dispatch ran)")
    headers = ["counter", "value"]
    lines = [[name, str(int(val))] for name, val in sorted(rows.items())]
    return report.format_table(headers, lines, left_cols=1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-op table from a legate_sparse_tpu trace file."
    )
    ap.add_argument("trace_file", help="Chrome-trace or newline-JSON file")
    ap.add_argument("--stream-gbs", type=float, default=None,
                    help="measured stream (triad) bandwidth for the "
                         "vs_stream roofline column; defaults to the "
                         "value embedded by bench.py when present")
    ap.add_argument("--events", action="store_true",
                    help="also list instant events (probe failures, "
                         "layout decisions, window declines)")
    ap.add_argument("--counters", action="store_true",
                    help="also dump the counter snapshot embedded in a "
                         "Chrome-trace file")
    ap.add_argument("--comm", action="store_true",
                    help="also render the comm.* ledger (per-op x "
                         "collective calls + predicted interconnect "
                         "bytes)")
    ap.add_argument("--plans", action="store_true",
                    help="also render the engine plan-cache table "
                         "(per-plan builds/hits/execs + executor "
                         "batching totals from the engine.* counters)")
    ap.add_argument("--resil", action="store_true",
                    help="also render the resilience ledger (per-site "
                         "faults/retries/breaker activity, shedding, "
                         "health verdicts from the resil.* counters)")
    ap.add_argument("--gateway", action="store_true",
                    help="also render the admission-gateway ledger "
                         "(per-tenant submitted/served/shed/error, "
                         "batch formation, per-reason rejections from "
                         "the gateway.* counters)")
    ap.add_argument("--autotune", action="store_true",
                    help="also render the autotune ledger (verdict "
                         "store activity, route hit/miss/decline "
                         "funnel, per-kernel routed dispatches from "
                         "the autotune.* counters)")
    ap.add_argument("--flows", action="store_true",
                    help="also render the causal-flow ledger (one row "
                         "per request trace id: span count, bracketing "
                         "span names, end-to-end wall time — obs v4 "
                         "flow arcs)")
    ap.add_argument("--slo", action="store_true",
                    help="also render the SLO burn ledger (latest "
                         "verdict per objective from slo.verdict "
                         "events + the exact slo.breach.* counters)")
    ap.add_argument("--graph", action="store_true",
                    help="also render the graph-analytics ledger "
                         "(per-algorithm runs/iters and per-semiring "
                         "distributed dispatch counts from the "
                         "graph.* counters)")
    ap.add_argument("--tenants", action="store_true",
                    help="also render the per-tenant attribution "
                         "ledger (attributed busy/wait time, comm "
                         "bytes, dispatch/compile counts and the "
                         "conservation check from the attrib.* "
                         "counters)")
    ap.add_argument("--placement", action="store_true",
                    help="also render the elastic-placement ledger "
                         "(controller steps/holds, migration count "
                         "and declared reshard bytes, routed "
                         "admissions from the placement.* counters)")
    ap.add_argument("--delta", action="store_true",
                    help="also render the streaming-mutation ledger "
                         "(update batches, applied/pending slots, "
                         "compaction merges and version swaps, comm "
                         "pricing from the delta.* counters)")
    ap.add_argument("--latency", action="store_true",
                    help="also render the latency-histogram ledger "
                         "(count/p50/p95/p99/max per op and shape "
                         "bucket from the lat.* histograms embedded "
                         "in a Chrome-trace artifact)")
    args = ap.parse_args(argv)

    records = report.load_records(args.trace_file)
    spans = [r for r in records if r.get("type") == "span"]

    stream_gbs = args.stream_gbs
    meta = {}
    try:
        with open(args.trace_file) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            meta = doc.get("otherData", {}) or {}
            if stream_gbs is None:
                stream_gbs = (meta.get("bench_result") or {}).get(
                    "stream_gbs")
    except (ValueError, OSError):
        pass  # newline-JSON / unreadable: no embedded metadata

    if not spans:
        print(f"{args.trace_file}: no span records "
              f"({len(records)} events total) — was tracing enabled "
              f"(LEGATE_SPARSE_TPU_OBS=1)?", file=sys.stderr)
        return 2

    print(report.render_table(report.aggregate(records),
                              stream_gbs=stream_gbs))

    if args.events:
        events = [r for r in records if r.get("type") == "event"]
        if events:
            print(f"\nevents ({len(events)}):")
            for r in events:
                attrs = r.get("attrs") or {}
                detail = " ".join(f"{k}={v}" for k, v in attrs.items())
                print(f"  {r['name']}  {detail}".rstrip())

    if args.counters and meta.get("counters"):
        print("\ncounters:")
        for name in sorted(meta["counters"]):
            print(f"  {name} = {meta['counters'][name]}")

    if args.comm:
        print("\ncomm ledger:")
        print(render_comm_table(meta.get("counters") or {}))

    if args.plans:
        print("\nengine plans:")
        print(report.render_plans_table(meta.get("counters") or {}))

    if args.resil:
        print("\nresilience ledger:")
        print(report.render_resil_table(meta.get("counters") or {}))

    if args.gateway:
        print("\ngateway ledger:")
        print(report.render_gateway_table(meta.get("counters") or {}))

    if args.autotune:
        print("\nautotune ledger:")
        print(render_autotune_table(meta.get("counters") or {}))

    if args.graph:
        print("\ngraph ledger:")
        print(render_graph_table(meta.get("counters") or {}))

    if args.tenants:
        print("\ntenant attribution:")
        print(report.render_tenants_table(meta.get("counters") or {}))

    if args.placement:
        print("\nplacement ledger:")
        print(report.render_placement_table(meta.get("counters") or {}))

    if args.delta:
        print("\ndelta ledger:")
        print(report.render_delta_table(meta.get("counters") or {}))

    if args.flows:
        print("\ncausal flows:")
        print(report.render_flows_table(records))

    if args.slo:
        print("\nslo ledger:")
        print(report.render_slo_table(meta.get("counters") or {},
                                      records))

    if args.latency:
        print("\nlatency histograms:")
        print(report.render_latency_table(meta.get("histograms") or {}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
