# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""On-chip irregular-path shoot-out — a thin CLI over the autotuner.

Races the autotune candidate registry (``csr-rowids`` / ``ell`` /
``sliced-ell``, ``legate_sparse_tpu/autotune/registry.py``) on the
irregular configs the reference's general path serves
(``src/sparse/array/csr/spmv.cc:36-44``), records each winning verdict
into the autotune store, and additionally times the Pallas BSR kernel
(``ops/bsr.py`` — not a registry candidate: it keeps unconditional
dispatch priority) across densities plus a clustered config (dense 8x8
sub-blocks scattered randomly — the FEM-node pattern) where BSR's
per-present-block population, not global density, sets the rate
(IRREGULAR.md law).

Candidate timing goes through ``autotune.measure_candidates`` — the
same harness ``tune()`` and the bench autotune phase use, so this tool
and the runtime agree by construction.  The winner is cross-checked
with the chained-fori_loop protocol (``bench_timing.py``), because on
this TPU tunnel ``block_until_ready`` can return at dispatch-ack
(bench.py header): a large gap between ``<label>_ms`` and
``winner_loop_ms`` flags the sync problem instead of hiding it.

Appends a JSON block to TPU_EVIDENCE.md.  Run from the repo root when
the accelerator answers: ``python tools/tune_irregular.py``.
``LEGATE_SPARSE_TPU_SHOOTOUT_TIMEOUT`` bounds the inner measurement
subprocess (seconds, default 3000).
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "TPU_EVIDENCE.md")

SHOOTOUT = r"""
import json
import numpy as np, jax, jax.numpy as jnp
import legate_sparse_tpu as sparse
from legate_sparse_tpu import autotune, gallery
from legate_sparse_tpu.bench_timing import loop_ms_per_iter
from legate_sparse_tpu.csr import csr_array
from legate_sparse_tpu.ops import spmv as spmv_ops
from legate_sparse_tpu.ops.bsr import bsr_pack, BsrStructure
from legate_sparse_tpu.settings import settings

settings.autotune = True
out = {"platform": jax.devices()[0].platform,
       "platform_fp": autotune.platform_fingerprint(), "configs": []}
rng = np.random.default_rng(0)

def measure(A, label):
    A.sum_duplicates()
    rows, cols = A.shape
    nnz = A.nnz
    x = jnp.asarray(rng.standard_normal(cols).astype(A.dtype))
    cfg = {"label": label, "rows": rows, "nnz": nnz,
           "density": round(nnz / (rows * cols), 6),
           "fingerprint": A._get_fingerprint().klass}
    useful_bytes = nnz * 8  # value + col index, CSR-equivalent terms

    # Candidate race through the autotune harness (the runtime's own
    # timing path); the winner becomes a stored verdict.
    try:
        timings = autotune.measure_candidates(A, x, warmup=1, trials=5)
        for lbl, ms in timings.items():
            k = lbl.replace("-", "_")
            cfg[k + "_ms"] = round(ms, 3)
            cfg[k + "_gbs"] = round(useful_bytes / ms / 1e6, 2)
        winner = min(timings, key=timings.get)
        cfg["verdict"] = winner
        key = autotune.key_for(A, "spmv")
        if key is not None:
            autotune.get_store().record(key, winner,
                                        timings_ms=timings, trials=5)
            cfg["verdict_key"] = key.key_id
        # Dispatch-ack cross-check: the chained-loop protocol cannot
        # be fooled by an early block_until_ready return.
        run = autotune.CANDIDATES[winner].run
        ms = loop_ms_per_iter(lambda v: run(A, v, "spmv"), x,
                              k_lo=2, k_hi=6)
        cfg["winner_loop_ms"] = round(ms, 3)
    except Exception as e:
        cfg["candidates_error"] = repr(e)[:300]

    # Pallas BSR (kept outside the registry: structure-specialized
    # priority path, measured here for the density law).
    pack = bsr_pack(np.asarray(A.data), np.asarray(A.indices),
                    np.asarray(A.indptr), A.shape, max_expand=1e9)
    if pack is not None:
        st = BsrStructure(*pack, rows, cols)
        cfg["nblocks"] = st.nblocks
        cfg["nnz_per_block"] = round(nnz / st.nblocks, 1)
        try:
            ms = loop_ms_per_iter(
                lambda v: st.matvec(v, interpret=False), x, k_lo=3, k_hi=13)
            cfg["bsr_ms"] = round(ms, 3)
            cfg["bsr_gbs"] = round(useful_bytes / ms / 1e6, 2)
            cfg["bsr_stream_gbs"] = round(
                (st.nblocks * 128 * 128 * 4) / ms / 1e6, 1)
        except Exception as e:
            cfg["bsr_error"] = repr(e)[:300]
    out["configs"].append(cfg)

def from_coo(r, c, n):
    order = np.lexsort((c, r))
    vals = np.ones(r.shape[0], np.float32)
    return csr_array((vals[order], (r[order], c[order])), shape=(n, n))

# Uniform random at increasing density, fixed 64 MB-ish footprint.
for n, d in [(1 << 14, 0.005), (1 << 14, 0.02), (1 << 13, 0.08)]:
    nnz = int(n * n * d)
    r = rng.integers(0, n, nnz); c = rng.integers(0, n, nnz)
    measure(from_coo(r, c, n), f"uniform_{n}_{d}")

# Power-law rows (the autotuner's home turf: flat ELL blows its
# padding budget, sliced ELL bins the skew away).
measure(gallery.powerlaw(1 << 18, nnz_per_row=8, rng=11),
        "powerlaw_2e18_w8")

# Clustered: dense 8x8 sub-blocks at random positions (FEM pattern),
# ~27 blocks per block-row like a 3-D stencil.
n = 1 << 15
bs, per_row = 8, 27
nb = (n // bs) * per_row
br = np.repeat(np.arange(n // bs), per_row)
bc = rng.integers(0, n // bs, nb)
rr = (br[:, None] * bs + np.arange(bs)[None, :]).ravel()
r = np.repeat(rr, bs)
c = ((bc[:, None] * bs + np.arange(bs)[None, :])[:, None, :]
     + np.zeros((1, bs, 1), np.int64)).ravel()
measure(from_coo(r, c, n), "clustered_fem_8x8")

# Hyper-sparse tail (the adversarial config): expect BSR over budget,
# the gather candidates are the ceiling; record it honestly.
n = 1 << 22
W = 11
nnz = n * W
r = np.repeat(np.arange(n), W)
c = rng.integers(0, n, nnz)
measure(from_coo(r, c, n), "hyper_sparse_2e22_W11")

out["verdicts"] = len(autotune.get_store())
print(json.dumps(out))
"""


def main() -> None:
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    code = ("from legate_sparse_tpu._platform import ACCEL_PROBE_CODE "
            "as c; exec(c)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=90,
                           capture_output=True, text=True, cwd=ROOT)
        ok = r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        print(f"{stamp}: TPU unreachable; nothing recorded")
        sys.exit(1)
    inner_timeout = int(os.environ.get(
        "LEGATE_SPARSE_TPU_SHOOTOUT_TIMEOUT", "3000"))
    try:
        r = subprocess.run([sys.executable, "-c", SHOOTOUT],
                           timeout=inner_timeout,
                           capture_output=True, text=True, cwd=ROOT)
        rc, out, err = r.returncode, r.stdout[-6000:], r.stderr[-2000:]
    except subprocess.TimeoutExpired:
        rc, out, err = 124, "", "timeout"
    with open(OUT, "a") as f:
        f.write(f"\n## Irregular shoot-out {stamp}\n"
                f"### (rc={rc})\n```json\n{out.strip()}\n```\n")
        if rc != 0:
            f.write(f"stderr: `{err[-800:]}`\n")
    print(f"recorded -> {OUT}")


if __name__ == "__main__":
    main()
