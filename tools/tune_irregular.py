# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""On-chip irregular-path shoot-out: XLA ELL gather vs block-sparse.

Measures random-sparsity CSR SpMV (the reference's general path,
``src/sparse/array/csr/spmv.cc:36-44``) through:
1. the XLA ELL gather kernel (``ops/spmv.py::ell_spmv``),
2. the Pallas BSR kernel (``ops/bsr.py``) across densities,
3. a clustered config (dense 8x8 sub-blocks scattered randomly — the
   FEM-node pattern) where BSR's per-present-block population, not
   global density, sets the rate (IRREGULAR.md law).

Appends a JSON block to TPU_EVIDENCE.md.  Run from the repo root when
the accelerator answers: ``python tools/tune_irregular.py``.
"""

from __future__ import annotations

import datetime
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "TPU_EVIDENCE.md")

SHOOTOUT = r"""
import json
import numpy as np, jax, jax.numpy as jnp
import scipy.sparse as sp
import legate_sparse_tpu as sparse
from legate_sparse_tpu.bench_timing import loop_ms_per_iter
from legate_sparse_tpu.ops import spmv as spmv_ops
from legate_sparse_tpu.ops.bsr import bsr_pack, BsrStructure

out = {"platform": jax.devices()[0].platform, "configs": []}
rng = np.random.default_rng(0)

def measure(A_sp, label):
    rows, cols = A_sp.shape
    nnz = A_sp.nnz
    x = jnp.asarray(rng.standard_normal(cols).astype(np.float32))
    cfg = {"label": label, "rows": rows, "nnz": nnz,
           "density": round(nnz / (rows * cols), 6)}
    useful_bytes = nnz * 8  # value + col index, CSR-equivalent terms

    # XLA ELL gather
    W = max(int(np.diff(A_sp.indptr).max()), 1)
    ell = spmv_ops.ell_pack_device(
        jnp.asarray(A_sp.data.astype(np.float32)),
        jnp.asarray(A_sp.indices.astype(np.int32)),
        jnp.asarray(A_sp.indptr.astype(np.int32)), rows, W)
    try:
        ms = loop_ms_per_iter(
            lambda v: spmv_ops.ell_spmv(ell[0], ell[1], ell[2], v),
            x, k_lo=2, k_hi=6)
        cfg["ell_xla_ms"] = round(ms, 3)
        cfg["ell_xla_gbs"] = round(useful_bytes / ms / 1e6, 2)
    except Exception as e:
        cfg["ell_xla_error"] = repr(e)[:200]

    # Pallas BSR
    pack = bsr_pack(A_sp.data, A_sp.indices, A_sp.indptr, A_sp.shape,
                    max_expand=1e9)
    if pack is not None:
        st = BsrStructure(*pack, rows, cols)
        cfg["nblocks"] = st.nblocks
        cfg["nnz_per_block"] = round(nnz / st.nblocks, 1)
        try:
            ms = loop_ms_per_iter(
                lambda v: st.matvec(v, interpret=False), x, k_lo=3, k_hi=13)
            cfg["bsr_ms"] = round(ms, 3)
            cfg["bsr_gbs"] = round(useful_bytes / ms / 1e6, 2)
            cfg["bsr_stream_gbs"] = round(
                (st.nblocks * 128 * 128 * 4) / ms / 1e6, 1)
        except Exception as e:
            cfg["bsr_error"] = repr(e)[:300]
    out["configs"].append(cfg)

# Uniform random at increasing density, fixed 64 MB-ish footprint.
for n, d in [(1 << 14, 0.005), (1 << 14, 0.02), (1 << 13, 0.08)]:
    nnz = int(n * n * d)
    r = rng.integers(0, n, nnz); c = rng.integers(0, n, nnz)
    A = sp.coo_matrix((np.ones(nnz, np.float32), (r, c)),
                      shape=(n, n)).tocsr()
    A.sum_duplicates()
    measure(A, f"uniform_{n}_{d}")

# Clustered: dense 8x8 sub-blocks at random positions (FEM pattern),
# ~27 blocks per block-row like a 3-D stencil.
n = 1 << 15
bs, per_row = 8, 27
nb = (n // bs) * per_row
br = np.repeat(np.arange(n // bs), per_row)
bc = rng.integers(0, n // bs, nb)
rr = (br[:, None] * bs + np.arange(bs)[None, :]).ravel()
r = np.repeat(rr, bs)
c = ((bc[:, None] * bs + np.arange(bs)[None, :])[:, None, :]
     + np.zeros((1, bs, 1), np.int64)).ravel()
A = sp.coo_matrix((np.ones(r.shape[0], np.float32), (r, c)),
                  shape=(n, n)).tocsr()
A.sum_duplicates()
measure(A, "clustered_fem_8x8")

# Hyper-sparse tail (the adversarial config): expect BSR over budget,
# XLA gather is the ceiling; record it honestly.
n = 1 << 22
W = 11
nnz = n * W
r = np.repeat(np.arange(n), W)
c = rng.integers(0, n, nnz)
A = sp.coo_matrix((np.ones(nnz, np.float32), (r, c)), shape=(n, n)).tocsr()
A.sum_duplicates()
measure(A, "hyper_sparse_2e22_W11")

print(json.dumps(out))
"""


def main() -> None:
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    code = ("from legate_sparse_tpu._platform import ACCEL_PROBE_CODE "
            "as c; exec(c)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=90,
                           capture_output=True, text=True, cwd=ROOT)
        ok = r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        print(f"{stamp}: TPU unreachable; nothing recorded")
        sys.exit(1)
    inner_timeout = int(os.environ.get(
        "LEGATE_SPARSE_TPU_SHOOTOUT_TIMEOUT", "3000"))
    try:
        r = subprocess.run([sys.executable, "-c", SHOOTOUT],
                           timeout=inner_timeout,
                           capture_output=True, text=True, cwd=ROOT)
        rc, out, err = r.returncode, r.stdout[-6000:], r.stderr[-2000:]
    except subprocess.TimeoutExpired:
        rc, out, err = 124, "", "timeout"
    with open(OUT, "a") as f:
        f.write(f"\n## Irregular shoot-out {stamp}\n"
                f"### (rc={rc})\n```json\n{out.strip()}\n```\n")
        if rc != 0:
            f.write(f"stderr: `{err[-800:]}`\n")
    print(f"recorded -> {OUT}")


if __name__ == "__main__":
    main()
