#!/bin/bash
# Probes the accelerator tunnel every 3 min; touches /tmp/tpu_alive when
# up and — the part that matters — fires tools/round5_capture.sh the
# first time a probe answers.  One-shot: after a capture chain records
# on-chip data (exit 0 -> marker file), later alive probes just log.
#
# Lock protocol: the lock dir carries the owner watcher's PID.  A lock
# is reclaimed only when that owner is dead AND no round*_capture.sh
# process is still running (a killed watcher can orphan a live capture
# chain — reclaiming under it would interleave two captures).  The EXIT
# trap removes the lock only if this process owns it.
cd "$(dirname "$0")/.."
mkdir -p evidence
LOCK=/tmp/tpu_capture.lock
DONE=/tmp/tpu_capture.done
STATE="${LEGATE_SPARSE_TPU_PROBE_STATE:-/tmp/lst_probe.$(id -u).json}"
cleanup() {
  if [ "$(cat "$LOCK/pid" 2>/dev/null)" = "$$" ]; then
    rm -rf "$LOCK"
  fi
}
trap cleanup EXIT
# Shared probe-verdict cache (read by _platform.ensure_live_backend):
# every watcher probe refreshes it, so CLI runs between watcher ticks
# skip their own 90s-per-attempt subprocess ladder.  $2 records the
# /tmp/tpu_alive marker state AT verdict time — a marker transition is
# the reader's staleness signal.
write_state() {
  # "exe" scopes the verdict to THIS watcher's interpreter: readers
  # running a different python (e.g. one that does have the TPU
  # plugin) ignore it and probe for themselves.
  printf '{"verdict": "%s", "ts": %s, "tunnel_marker": %s, "source": "watcher", "exe": "%s"}\n' \
    "$1" "$(date +%s)" "$2" "$(command -v python)" > "$STATE.tmp" \
    && mv "$STATE.tmp" "$STATE"
}
while true; do
  # -u so the import-ok marker survives a timeout kill: a cached
  # "dead" verdict must only come from a probe that got PAST the jax
  # import — a watcher running in a broken environment (no jax on
  # PATH, bad venv) must not poison every CLI run's probe cache.
  probe_out=$(timeout 60 python -u -c "import jax, jax.numpy as jnp; print('import-ok'); ds = jax.devices(); assert ds and ds[0].platform != 'cpu', ds; assert float(jnp.ones((8, 128)).sum()) == 1024.0" 2>/dev/null)
  if [ $? -eq 0 ]; then
    date -u +"%Y-%m-%dT%H:%M:%SZ alive" >> /tmp/tpu_watch.log
    touch /tmp/tpu_alive
    write_state live true
    if [ ! -e "$DONE" ]; then
      owner=$(cat "$LOCK/pid" 2>/dev/null)
      if [ -d "$LOCK" ] && [ -n "$owner" ] && ! kill -0 "$owner" 2>/dev/null \
         && ! pgrep -f "tools/round[0-9]_capture.sh" >/dev/null 2>&1; then
        rm -rf "$LOCK"   # dead owner, no orphaned capture: reclaim
      fi
      if mkdir "$LOCK" 2>/dev/null; then
        echo $$ > "$LOCK/pid"
        if LEGATE_SPARSE_TPU_PROBE_FORCE=1 bash tools/round5_capture.sh >> evidence/round5_capture.log 2>&1; then
          touch "$DONE"
        fi
        rm -rf "$LOCK"
      fi
    fi
  else
    date -u +"%Y-%m-%dT%H:%M:%SZ down" >> /tmp/tpu_watch.log
    rm -f /tmp/tpu_alive
    case "$probe_out" in
      *import-ok*) write_state dead false ;;   # real device failure/stall
      *) ;;  # env-broken watcher: leave the cache alone, CLIs self-probe
    esac
  fi
  sleep 180
done
