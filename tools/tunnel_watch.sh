#!/bin/bash
# Probes the accelerator tunnel every 5 min; touches /tmp/tpu_alive when up.
while true; do
  if timeout 60 python -c "import jax, jax.numpy as jnp; ds = jax.devices(); assert ds and ds[0].platform != 'cpu', ds; assert float(jnp.ones((8, 128)).sum()) == 1024.0" 2>/dev/null; then
    date -u +"%Y-%m-%dT%H:%M:%SZ alive" >> /tmp/tpu_watch.log
    touch /tmp/tpu_alive
  else
    date -u +"%Y-%m-%dT%H:%M:%SZ down" >> /tmp/tpu_watch.log
    rm -f /tmp/tpu_alive
  fi
  sleep 300
done
