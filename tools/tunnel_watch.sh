#!/bin/bash
# Probes the accelerator tunnel every 3 min; touches /tmp/tpu_alive when
# up and — the part that matters — fires tools/round5_capture.sh the
# first time a probe answers.  One-shot: after a capture chain records
# on-chip data (exit 0 -> marker file), later alive probes just log.
#
# Lock protocol: the lock dir carries the owner watcher's PID.  A lock
# is reclaimed only when that owner is dead AND no round*_capture.sh
# process is still running (a killed watcher can orphan a live capture
# chain — reclaiming under it would interleave two captures).  The EXIT
# trap removes the lock only if this process owns it.
cd "$(dirname "$0")/.."
mkdir -p evidence
LOCK=/tmp/tpu_capture.lock
DONE=/tmp/tpu_capture.done
cleanup() {
  if [ "$(cat "$LOCK/pid" 2>/dev/null)" = "$$" ]; then
    rm -rf "$LOCK"
  fi
}
trap cleanup EXIT
while true; do
  if timeout 60 python -c "import jax, jax.numpy as jnp; ds = jax.devices(); assert ds and ds[0].platform != 'cpu', ds; assert float(jnp.ones((8, 128)).sum()) == 1024.0" 2>/dev/null; then
    date -u +"%Y-%m-%dT%H:%M:%SZ alive" >> /tmp/tpu_watch.log
    touch /tmp/tpu_alive
    if [ ! -e "$DONE" ]; then
      owner=$(cat "$LOCK/pid" 2>/dev/null)
      if [ -d "$LOCK" ] && [ -n "$owner" ] && ! kill -0 "$owner" 2>/dev/null \
         && ! pgrep -f "tools/round[0-9]_capture.sh" >/dev/null 2>&1; then
        rm -rf "$LOCK"   # dead owner, no orphaned capture: reclaim
      fi
      if mkdir "$LOCK" 2>/dev/null; then
        echo $$ > "$LOCK/pid"
        if bash tools/round5_capture.sh >> evidence/round5_capture.log 2>&1; then
          touch "$DONE"
        fi
        rm -rf "$LOCK"
      fi
    fi
  else
    date -u +"%Y-%m-%dT%H:%M:%SZ down" >> /tmp/tpu_watch.log
    rm -f /tmp/tpu_alive
  fi
  sleep 180
done
