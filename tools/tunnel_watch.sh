#!/bin/bash
# Probes the accelerator tunnel every 3 min; touches /tmp/tpu_alive when
# up and — the part that matters — fires tools/round3_capture.sh the
# first time a probe answers.  One-shot: after a capture chain COMPLETES
# (marker file), later alive probes just log.  A stale lock (watcher or
# capture killed mid-run) is reclaimed after 4h so an interrupted run
# retries on the next window.  The capture tool appends each phase's
# result to TPU_EVIDENCE.md as it finishes, so even a short tunnel
# window records something.
cd "$(dirname "$0")/.."
mkdir -p evidence
LOCK=/tmp/tpu_capture.lock
DONE=/tmp/tpu_capture.done
trap 'rmdir "$LOCK" 2>/dev/null' EXIT
while true; do
  if timeout 60 python -c "import jax, jax.numpy as jnp; ds = jax.devices(); assert ds and ds[0].platform != 'cpu', ds; assert float(jnp.ones((8, 128)).sum()) == 1024.0" 2>/dev/null; then
    date -u +"%Y-%m-%dT%H:%M:%SZ alive" >> /tmp/tpu_watch.log
    touch /tmp/tpu_alive
    if [ ! -e "$DONE" ]; then
      # Reclaim a lock older than 4h: its owner is dead or wedged.
      if [ -d "$LOCK" ] && [ -n "$(find "$LOCK" -maxdepth 0 -mmin +240 2>/dev/null)" ]; then
        rmdir "$LOCK" 2>/dev/null
      fi
      if mkdir "$LOCK" 2>/dev/null; then
        if bash tools/round3_capture.sh >> evidence/round3_capture.log 2>&1; then
          touch "$DONE"
        fi
        rmdir "$LOCK" 2>/dev/null
      fi
    fi
  else
    date -u +"%Y-%m-%dT%H:%M:%SZ down" >> /tmp/tpu_watch.log
    rm -f /tmp/tpu_alive
  fi
  sleep 180
done
