# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""planverify: StableHLO/jaxpr contract verifier for compiled kernels
and dist plans (docs/VERIFY.md).

Lowers — never executes — every registered kernel and dist-plan shape
and checks the IR against committed per-program contracts: collective
schedule, exact comm bytes vs obs/comm, transfer freedom, and dtype
discipline.  Import surface:

- ``tools.verify.contracts`` — jax-free contract store (safe for the
  sparselint ``plan-contract`` rule);
- ``tools.verify.catalog`` — the program catalog (imports jax lazily
  at build time);
- ``tools.verify.runner`` / ``tools.verify.cli`` — the verify
  pipeline and CLI (``python tools/planverify.py``).

This ``__init__`` intentionally imports none of them: listing
contracts must never initialize a jax backend.
"""
