# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""The verified-program catalog: every kernel the autotune registry
dispatches and every dist-plan shape, lowered — never executed.

Each entry builds a ``Built``: the program's StableHLO text (via
``jax.jit(...).lower()``), its jaxpr, and the ``obs/comm`` byte
prediction the comm-bytes rule cross-checks.  Builders go through the
SAME code paths production compiles:

- registry kernels through ``engine.plan_cache.plan_program`` /
  ``lower_plan`` (single spec source — the contract is checked against
  exactly what a plan cache miss would compile);
- dist plans through the public dispatchers (``dist_spmv`` /
  ``dist_spmm``) on small fixture operands over the 8-device virtual
  CPU mesh, one program per ``DIST_PLAN_SHAPES`` /
  ``SPGEMM_PLAN_SHAPES`` triple;
- solver cycle bodies through the exact loop-body builders the solvers
  dispatch (``linalg._cg_builders`` lowered against sharded
  ``ShapeDtypeStruct`` state, ``linalg._gmres_cycle``), so
  transfer-freedom is proven for the code that runs *inside* the
  while_loop, where a host round-trip would sync every iteration.

Two prediction scopes, and why (docs/VERIFY.md):

- ``predicted``: collectives the program emits explicitly (shard_map
  bodies).  These are visible at lower time and the comm-bytes rule
  requires EXACT byte equality against ``obs/comm``.  ``None`` marks a
  program whose collectives sit inside a traced-once loop body that
  re-executes (GMRES Arnoldi), where per-dispatch totals are not a
  lower-time quantity — schedule/transfer/dtype checks still apply.
- ``deferred``: collectives the model prices that the SPMD partitioner
  only materializes at COMPILE time (scalar ``jnp.vdot`` psums on
  sharded vectors outside shard_map).  They are absent from lowered
  IR by construction, so they are recorded in the contract as modeled
  volumes rather than IR-checked ones.

jax (and the package) import lazily inside builders: listing the
catalog or resolving contract names must not initialize a backend.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# Sources shared by every program: the bytes bridge and the model.
_COMM = ("legate_sparse_tpu/obs/comm.py",)
_KERNEL_SRC = ("legate_sparse_tpu/ops/spmv.py",
               "legate_sparse_tpu/engine/plan_cache.py",
               "legate_sparse_tpu/autotune/registry.py") + _COMM
_DIST_SRC = ("legate_sparse_tpu/parallel/dist_csr.py",
             "legate_sparse_tpu/parallel/mesh.py") + _COMM
_SOLVER_SRC = _DIST_SRC + ("legate_sparse_tpu/linalg.py",)
_SPGEMM_SRC = ("legate_sparse_tpu/parallel/dist_spgemm.py",
               "legate_sparse_tpu/parallel/mesh.py") + _COMM

MESH_DEVICES = 8          # virtual CPU mesh every dist fixture uses
GRID = (2, 4)             # 2-d-block fixture grid
N_1D = 64                 # 1-d fixture order (rows_per_shard = 8)
N_2D = 96                 # 2-d fixture order (matches tests' grid size)
CG_CONV_TEST_ITERS = 25   # dist_cg's default — part of the body program
GMRES_RESTART = 4


@dataclass(frozen=True)
class Program:
    """One contracted program: identity + provenance, no jax."""

    pid: str                      # e.g. "dist/spmv/1d-row/halo/f32"
    kind: str                     # "kernel" | "dist"
    sources: Tuple[str, ...]      # repo-relative files that define it


@dataclass
class Built:
    """One program's lowered artifacts + model prediction."""

    hlo: str
    jaxpr: Any
    # Exact model volumes for explicitly-lowered collectives, keyed by
    # ledger kind; None = loop-replayed collectives, bytes not a
    # lower-time quantity (schedule is still contracted).
    predicted: Optional[Dict[str, int]]
    # Modeled volumes the partitioner inserts post-lowering.
    deferred: Dict[str, int] = field(default_factory=dict)
    # Declared accumulator widenings ("bf16->f32") the dtype rule
    # permits for this program.
    widening_allowed: Tuple[str, ...] = ()
    notes: Dict[str, Any] = field(default_factory=dict)


_PROGRAMS: List[Program] = []
_BUILDERS: Dict[str, Callable[[], Built]] = {}
_BUILT: Dict[str, Built] = {}


def _program(pid: str, kind: str, sources: Tuple[str, ...]):
    def deco(fn):
        _PROGRAMS.append(Program(pid=pid, kind=kind, sources=sources))
        _BUILDERS[pid] = fn
        return fn
    return deco


def all_programs() -> List[Program]:
    return list(_PROGRAMS)


def get_program(pid: str) -> Program:
    for p in _PROGRAMS:
        if p.pid == pid:
            return p
    raise KeyError(pid)


def build(pid: str) -> Built:
    """Build (and memoize) one program's lowered artifacts."""
    if pid not in _BUILT:
        _BUILT[pid] = _BUILDERS[pid]()
    return _BUILT[pid]


def _require_devices():
    import jax

    n = len(jax.devices())
    if n < MESH_DEVICES:
        raise RuntimeError(
            f"planverify needs a {MESH_DEVICES}-device virtual mesh "
            f"(got {n}); run via tools/planverify.py, which pins "
            f"XLA_FLAGS before jax initializes")


# ------------------------------------------------------------------ #
# shared fixtures (memoized; device_put of tiny arrays only —
# contracted programs themselves are lowered, never run)
# ------------------------------------------------------------------ #

_FIX: Dict[str, Any] = {}


def _banded_np(n: int, dtype="float32"):
    import legate_sparse_tpu as sparse
    import numpy as np

    return sparse.diags(
        [np.ones(n - 1), np.full(n, 4.0), np.ones(n - 1)], [-1, 0, 1],
        shape=(n, n), format="csr", dtype=np.dtype(dtype))


def _fix(key: str, make: Callable[[], Any]) -> Any:
    if key not in _FIX:
        _FIX[key] = make()
    return _FIX[key]


def _row_mesh():
    from legate_sparse_tpu.parallel import make_row_mesh

    _require_devices()
    import jax

    return _fix("row_mesh", lambda: make_row_mesh(
        jax.devices()[:MESH_DEVICES]))


def _grid_mesh():
    from legate_sparse_tpu.parallel import make_grid_mesh

    _require_devices()
    return _fix("grid_mesh", lambda: make_grid_mesh(*GRID))


def _dist_A(key: str, **shard_kwargs):
    from legate_sparse_tpu.parallel import shard_csr

    def make():
        if shard_kwargs.get("layout") in ("2d-block", "1d-col"):
            from legate_sparse_tpu.parallel import make_grid_mesh

            mesh = (_grid_mesh() if shard_kwargs["layout"] == "2d-block"
                    else _fix("col_mesh",
                              lambda: make_grid_mesh(1, MESH_DEVICES)))
            n = N_2D if shard_kwargs["layout"] == "2d-block" else N_1D
            return shard_csr(_banded_np(n), mesh=mesh, **shard_kwargs)
        return shard_csr(_banded_np(N_1D), mesh=_row_mesh(),
                         **shard_kwargs)

    return _fix(key, make)


def _spmv_predicted(dA, itemsize: int = 4, cols: int = 1):
    from legate_sparse_tpu.parallel.dist_csr import spmv_comm_volumes

    xl = (dA.rows_padded // dA.num_shards) * cols
    vols = spmv_comm_volumes(dA, xl, itemsize, cols=cols)
    return {k: v for k, v in vols.items() if v > 0}


def _lower_dist_spmv(dA, cols: int = 1):
    import jax
    import numpy as np

    from legate_sparse_tpu.parallel.dist_csr import (
        dist_spmm, dist_spmv, shard_dense, shard_vector,
    )

    n = dA.shape[0]
    if cols == 1:
        x = shard_vector(np.ones(n, np.float32), dA.mesh,
                         dA.rows_padded, layout=dA.layout)
        fn = lambda v: dist_spmv(dA, v)            # noqa: E731
    else:
        x = shard_dense(np.ones((n, cols), np.float32), dA.mesh,
                        dA.rows_padded)
        fn = lambda v: dist_spmm(dA, v)            # noqa: E731
    hlo = jax.jit(fn).lower(x).as_text()
    jaxpr = jax.make_jaxpr(fn)(x)
    return hlo, jaxpr


def _spmv_program(pid: str, fixture_key: str, **shard_kwargs):
    @_program(pid, "dist", _DIST_SRC)
    def _build():
        dA = _dist_A(fixture_key, **shard_kwargs)
        hlo, jaxpr = _lower_dist_spmv(dA)
        return Built(hlo=hlo, jaxpr=jaxpr,
                     predicted=_spmv_predicted(dA),
                     notes={"layout": dA.layout,
                            "shards": dA.num_shards})


# ------------------------------------------------------------------ #
# kernel programs (autotune registry labels x dtype class)
# ------------------------------------------------------------------ #

def _kernel_build(op: str, dtype: str, k_b: int = 1):
    import jax

    from legate_sparse_tpu.engine.plan_cache import (
        PlanKey, lower_plan, plan_program,
    )

    key = PlanKey(op, dtype, N_1D, N_1D, 4 * N_1D, k_b=k_b)
    hlo = lower_plan(key).as_text()
    fn, specs, static, _name = plan_program(key)
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a, **static))(*specs)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 notes={"plan_id": key.plan_id})


for _op, _pid_op, _dt, _pid_dt, _k in (
        ("spmv", "spmv", "float32", "f32", 1),
        ("spmv", "spmv", "bfloat16", "bf16", 1),
        ("spmm", "spmm", "float32", "f32", 4),
        ("spmv_multi", "spmv-multi", "float32", "f32", 3),
):
    _program(f"kernel/csr-rowids/{_pid_op}/{_pid_dt}", "kernel",
             _KERNEL_SRC)(
        lambda op=_op, dt=_dt, k=_k: _kernel_build(op, dt, k_b=k))


def _ell_build(op: str, dtype: str, k: int = 4):
    import jax
    import numpy as np

    from legate_sparse_tpu.ops.spmv import ell_spmm, ell_spmv

    sds = jax.ShapeDtypeStruct
    dt, W = np.dtype(dtype), 3
    specs = (sds((N_1D, W), dt), sds((N_1D, W), np.int32),
             sds((N_1D,), np.int32))
    if op == "spmv":
        fn, specs = ell_spmv, specs + (sds((N_1D,), dt),)
    else:
        fn, specs = ell_spmm, specs + (sds((N_1D, k), dt),)
    hlo = jax.jit(fn).lower(*specs).as_text()
    jaxpr = jax.make_jaxpr(fn)(*specs)
    # jnp.sum's row reduction deliberately accumulates bf16 in f32
    # (upcast -> reduce -> cast back): the declared-accumulator case.
    allowed = ("bf16->f32",) if dtype == "bfloat16" else ()
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 widening_allowed=allowed)


for _op, _dt, _pid_dt in (("spmv", "float32", "f32"),
                          ("spmv", "bfloat16", "bf16"),
                          ("spmm", "float32", "f32")):
    _program(f"kernel/ell/{_op}/{_pid_dt}", "kernel", _KERNEL_SRC)(
        lambda op=_op, dt=_dt: _ell_build(op, dt))


@_program("kernel/sliced-ell/spmv/f32", "kernel", _KERNEL_SRC)
def _build_sliced_ell():
    import jax
    import numpy as np

    from legate_sparse_tpu.ops.spmv import (
        sliced_ell_pack, sliced_ell_spmv,
    )

    import jax.numpy as jnp

    A = _banded_np(N_1D)
    bins = sliced_ell_pack(jnp.asarray(A.data),
                           jnp.asarray(A.indices), A.indptr, N_1D)
    x = jax.ShapeDtypeStruct((N_1D,), np.float32)
    hlo = sliced_ell_spmv.lower(bins, x, rows=N_1D).as_text()
    jaxpr = jax.make_jaxpr(
        lambda b, v: sliced_ell_spmv(b, v, rows=N_1D))(bins, x)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 notes={"bins": len(bins)})


def _f32acc_build(op: str):
    """Low-byte-storage kernel programs (the autotune labels
    ``csr-rowids-bf16`` / ``ell-bf16``): bf16 values with int16
    column indices — the representation ``csr_array.compress``
    produces — against an f32 operand.  Lowered at the jitted entry
    points directly: the engine plan cache declines promotion, so the
    autotune registry is the only dispatcher of these variants."""
    import jax
    import numpy as np

    from legate_sparse_tpu.ops import spmv as _ops

    sds = jax.ShapeDtypeStruct
    bf16, f32 = np.dtype("bfloat16"), np.dtype(np.float32)
    if op in ("spmv", "spmm"):
        nnz = 4 * N_1D
        fn = (_ops.csr_spmv_rowids_f32acc if op == "spmv"
              else _ops.csr_spmm_rowids_f32acc)
        specs = (sds((nnz,), bf16), sds((nnz,), np.int16),
                 sds((nnz,), np.int32),
                 sds((N_1D,), f32) if op == "spmv"
                 else sds((N_1D, 4), f32))
    else:                                   # flat ELL
        W = 3
        fn = _ops.ell_spmv_f32acc
        specs = (sds((N_1D, W), bf16), sds((N_1D, W), np.int16),
                 sds((N_1D,), np.int32), sds((N_1D,), f32))
    kw = {"rows": N_1D} if op in ("spmv", "spmm") else {}
    hlo = fn.lower(*specs, **kw).as_text()
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a, **kw))(*specs)
    # The declared accumulator: products and segment/row reductions
    # run in f32, the out narrows to result_type(data, x) == f32.
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 widening_allowed=("bf16->f32",))


for _op, _pid in (("spmv", "kernel/csr-rowids-bf16/spmv"),
                  ("spmm", "kernel/csr-rowids-bf16/spmm"),
                  ("ell", "kernel/ell-bf16/spmv")):
    _program(_pid, "kernel", _KERNEL_SRC)(
        lambda op=_op: _f32acc_build(op))


_SEMIRING_SRC = _KERNEL_SRC + ("legate_sparse_tpu/graph/semiring.py",)


def _semiring_kernel_build(label: str):
    """Semiring kernel programs (the autotune labels ``semiring-csr``
    / ``semiring-ell`` / ``semiring-sliced-ell`` — docs/GRAPH.md),
    lowered at the jitted entry points directly (like the ``*-bf16``
    variants: the graph dispatcher and autotune registry are their
    only callers) under the min-plus pair, the catalog entry whose
    reduction is NOT a sum — so the contract pins the generalized
    segment/row-min program, not the plus-times degenerate case."""
    import jax
    import numpy as np

    from legate_sparse_tpu.ops import spmv as _ops

    sds = jax.ShapeDtypeStruct
    f32 = np.dtype(np.float32)
    kw = {"add": "min", "mul": "plus"}
    if label == "semiring-csr":
        nnz = 4 * N_1D
        fn = _ops.csr_semiring_spmv_rowids_masked
        specs = (sds((nnz,), f32), sds((nnz,), np.int32),
                 sds((nnz,), np.int32), sds((), np.int32),
                 sds((N_1D,), f32))
        kw["rows"] = N_1D
    else:                                   # flat ELL
        W = 3
        fn = _ops.ell_semiring_spmv
        specs = (sds((N_1D, W), f32), sds((N_1D, W), np.int32),
                 sds((N_1D,), np.int32), sds((N_1D,), f32))
    hlo = fn.lower(*specs, **kw).as_text()
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a, **kw))(*specs)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 notes={"semiring": "min-plus"})


for _label, _pid in (("semiring-csr", "kernel/semiring-csr/spmv/f32"),
                     ("semiring-ell", "kernel/semiring-ell/spmv/f32")):
    _program(_pid, "kernel", _SEMIRING_SRC)(
        lambda label=_label: _semiring_kernel_build(label))


@_program("kernel/semiring-sliced-ell/spmv/f32", "kernel",
          _SEMIRING_SRC)
def _build_semiring_sliced_ell():
    import jax
    import numpy as np

    import jax.numpy as jnp

    from legate_sparse_tpu.ops.spmv import (
        sliced_ell_pack, sliced_ell_semiring_spmv,
    )

    A = _banded_np(N_1D)
    bins = sliced_ell_pack(jnp.asarray(A.data),
                           jnp.asarray(A.indices), A.indptr, N_1D)
    x = jax.ShapeDtypeStruct((N_1D,), np.float32)
    kw = {"rows": N_1D, "add": "min", "mul": "plus"}
    hlo = sliced_ell_semiring_spmv.lower(bins, x, **kw).as_text()
    jaxpr = jax.make_jaxpr(
        lambda b, v: sliced_ell_semiring_spmv(b, v, **kw))(bins, x)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 notes={"bins": len(bins), "semiring": "min-plus"})


@_program("kernel/coo-segment/spmv/f32", "kernel",
          _KERNEL_SRC + ("legate_sparse_tpu/delta/core.py",))
def _build_coo_segment():
    """The delta layer's side-buffer serving kernel
    (docs/MUTATION.md): masked COO segment-sum over one pow2 capacity
    bucket.  The contract pins the two-term schedule's delta half —
    the masked product (exact zero beyond ``valid_nnz``, never
    ``0*x``), the sorted ``segment_sum`` over ``rows`` segments that
    drops the out-of-range sentinel padding, and f32 dtype discipline
    end to end — so a mutation-path refactor that changes what a
    buffered update lowers to fails verify before it ships."""
    import jax
    import numpy as np

    from legate_sparse_tpu.ops.spmv import coo_spmv_segment

    sds = jax.ShapeDtypeStruct
    f32 = np.dtype(np.float32)
    cap = 64                     # one pow2 capacity bucket
    specs = (sds((cap,), f32), sds((cap,), np.int32),
             sds((cap,), np.int32), sds((), np.int32),
             sds((N_1D,), f32))
    kw = {"rows": N_1D}
    hlo = coo_spmv_segment.lower(*specs, **kw).as_text()
    jaxpr = jax.make_jaxpr(
        lambda *a: coo_spmv_segment(*a, **kw))(*specs)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 notes={"capacity_bucket": cap})


@_program("kernel/sliced-ell-bf16/spmv", "kernel", _KERNEL_SRC)
def _build_sliced_ell_bf16():
    import jax
    import numpy as np

    import jax.numpy as jnp

    from legate_sparse_tpu.ops.spmv import (
        sliced_ell_pack, sliced_ell_spmv_f32acc,
    )

    C = _banded_np(N_1D).compress()         # bf16 values, int16 cols
    bins = sliced_ell_pack(jnp.asarray(C.data),
                           jnp.asarray(C.indices), C.indptr, N_1D)
    x = jax.ShapeDtypeStruct((N_1D,), np.float32)
    hlo = sliced_ell_spmv_f32acc.lower(bins, x, rows=N_1D).as_text()
    jaxpr = jax.make_jaxpr(
        lambda b, v: sliced_ell_spmv_f32acc(b, v, rows=N_1D))(bins, x)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={},
                 widening_allowed=("bf16->f32",),
                 notes={"bins": len(bins)})


# ------------------------------------------------------------------ #
# dist_spmv / dist_spmm plan shapes
# ------------------------------------------------------------------ #

_spmv_program("dist/spmv/1d-row/halo/f32", "dA_halo")
_spmv_program("dist/spmv/1d-row/all-gather/f32", "dA_ag",
              force_all_gather=True)
_spmv_program("dist/spmv/1d-row/precise/f32", "dA_precise",
              precise=True)
_spmv_program("dist/spmv/1d-col/panel/f32", "dA_1dcol",
              layout="1d-col")
_spmv_program("dist/spmv/2d-block/panel/f32", "dA_2d",
              layout="2d-block")


@_program("dist/spmv/2d-block/panel/bf16", "dist", _DIST_SRC)
def _build_spmv_2d_bf16():
    """Compressed-panel realization of the SAME ("dist_spmv",
    "2d-block", "panel") plan shape: bf16 panel values + int16
    block-local column indices (``compress()`` upstream of
    ``shard_csr``), bf16 x — every collective moves exactly half the
    f32 program's bytes, priced by the same ledger formulas at
    itemsize 2."""
    import jax
    import jax.numpy as jnp

    from legate_sparse_tpu.parallel import shard_csr
    from legate_sparse_tpu.parallel.dist_csr import (
        dist_spmv, shard_vector,
    )

    dA = _fix("dA_2d_bf16", lambda: shard_csr(
        _banded_np(N_2D).compress(), mesh=_grid_mesh(),
        layout="2d-block"))
    x = shard_vector(jnp.ones(dA.shape[0], jnp.bfloat16), dA.mesh,
                     dA.rows_padded, layout=dA.layout)
    fn = lambda v: dist_spmv(dA, v)                 # noqa: E731
    hlo = jax.jit(fn).lower(x).as_text()
    jaxpr = jax.make_jaxpr(fn)(x)
    return Built(hlo=hlo, jaxpr=jaxpr,
                 predicted=_spmv_predicted(dA, itemsize=2),
                 widening_allowed=("bf16->f32",),
                 notes={"layout": dA.layout, "shards": dA.num_shards,
                        "cols_dtype": str(dA.cols.dtype)})


@_program("dist/spmm/1d-row/halo/f32", "dist", _DIST_SRC)
def _build_spmm_halo():
    k = 4
    dA = _dist_A("dA_halo")
    hlo, jaxpr = _lower_dist_spmv(dA, cols=k)
    return Built(hlo=hlo, jaxpr=jaxpr,
                 predicted=_spmv_predicted(dA, cols=k),
                 notes={"k": k})


# ------------------------------------------------------------------ #
# semiring dist_spmv / dist_spmm plan shapes (docs/GRAPH.md): the
# DIST_PLAN_SHAPES ("dist_spmv_semiring", ...) triples, lowered
# through the public dispatchers under min-plus — the catalog entry
# whose 2-d-block cross-shard reduction is a pmin all_reduce instead
# of the psum_scatter (the wire program the semiring generalization
# actually changes; 1-d layouts realize x identically to plus-times).
# ------------------------------------------------------------------ #

_DIST_SEMIRING_SRC = _DIST_SRC + (
    "legate_sparse_tpu/graph/semiring.py",)


def _semiring_spmv_predicted(dA, cols: int = 1):
    from legate_sparse_tpu.parallel.dist_csr import (
        semiring_spmv_comm_volumes,
    )

    vols = semiring_spmv_comm_volumes(dA, 4, 4, "pmin", cols=cols)
    return {k: v for k, v in vols.items() if v > 0}


def _lower_dist_spmv_semiring(dA, cols: int = 1):
    import jax
    import numpy as np

    from legate_sparse_tpu.parallel.dist_csr import (
        dist_spmm, dist_spmv, shard_dense, shard_vector,
    )

    n = dA.shape[0]
    if cols == 1:
        x = shard_vector(np.ones(n, np.float32), dA.mesh,
                         dA.rows_padded, layout=dA.layout)
        fn = lambda v: dist_spmv(dA, v,             # noqa: E731
                                 semiring="min-plus")
    else:
        x = shard_dense(np.ones((n, cols), np.float32), dA.mesh,
                        dA.rows_padded)
        fn = lambda v: dist_spmm(dA, v,             # noqa: E731
                                 semiring="min-plus")
    hlo = jax.jit(fn).lower(x).as_text()
    jaxpr = jax.make_jaxpr(fn)(x)
    return hlo, jaxpr


def _spmv_semiring_program(pid: str, fixture_key: str, **shard_kwargs):
    @_program(pid, "dist", _DIST_SEMIRING_SRC)
    def _build():
        dA = _dist_A(fixture_key, **shard_kwargs)
        hlo, jaxpr = _lower_dist_spmv_semiring(dA)
        return Built(hlo=hlo, jaxpr=jaxpr,
                     predicted=_semiring_spmv_predicted(dA),
                     notes={"layout": dA.layout,
                            "shards": dA.num_shards,
                            "semiring": "min-plus"})


_spmv_semiring_program("dist/spmv-semiring/1d-row/halo/f32", "dA_halo")
_spmv_semiring_program("dist/spmv-semiring/1d-row/all-gather/f32",
                       "dA_ag", force_all_gather=True)
_spmv_semiring_program("dist/spmv-semiring/1d-row/precise/f32",
                       "dA_precise", precise=True)
_spmv_semiring_program("dist/spmv-semiring/1d-col/panel/f32",
                       "dA_1dcol", layout="1d-col")
_spmv_semiring_program("dist/spmv-semiring/2d-block/panel/f32",
                       "dA_2d", layout="2d-block")


@_program("dist/spmm-semiring/1d-row/halo/f32", "dist",
          _DIST_SEMIRING_SRC)
def _build_spmm_semiring_halo():
    k = 4
    dA = _dist_A("dA_halo")
    hlo, jaxpr = _lower_dist_spmv_semiring(dA, cols=k)
    return Built(hlo=hlo, jaxpr=jaxpr,
                 predicted=_semiring_spmv_predicted(dA, cols=k),
                 notes={"k": k, "semiring": "min-plus"})


@_program("dist/reshard/1d-row/chunk-permute/f32", "dist",
          _DIST_SRC + ("legate_sparse_tpu/parallel/reshard.py",))
def _build_reshard_chunk_permute():
    """THE cached chunk-permute reshard program (``parallel/
    reshard.py``): one ``ppermute`` over the flat mesh moving each
    vector chunk from its source device to its destination-placement
    owner.  The fixture destination is the rotate-by-one device order,
    so every chunk moves — the worst case the static prediction
    (``obs.comm.reshard_volumes``) must price exactly.  The contract
    pins the collective schedule: exactly one collective-permute, all
    pairs moving, no other transfers."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from legate_sparse_tpu.obs.comm import reshard_volumes
    from legate_sparse_tpu.parallel.dist_csr import shard_vector
    from legate_sparse_tpu.parallel.reshard import (
        _chunk_permute_program,
    )

    mesh = _row_mesh()
    devs = list(np.asarray(mesh.devices).reshape(-1))
    dst = _fix("rot_mesh", lambda: Mesh(
        np.asarray(devs[1:] + devs[:1]), mesh.axis_names))
    x = shard_vector(np.ones(N_1D, np.float32), mesh, N_1D)
    fn, _pairs, moved = _chunk_permute_program(mesh, dst)
    hlo = fn.lower(x).as_text()
    jaxpr = jax.make_jaxpr(fn)(x)
    return Built(hlo=hlo, jaxpr=jaxpr,
                 predicted=reshard_volumes(
                     moved_chunks=moved,
                     chunk_elems=N_1D // MESH_DEVICES, itemsize=4,
                     shards=MESH_DEVICES),
                 notes={"moved_pairs": moved,
                        "shards": MESH_DEVICES})


# ------------------------------------------------------------------ #
# solver cycle bodies (transfer-freedom inside the loop)
# ------------------------------------------------------------------ #

def _cg_state_specs(dA):
    """ShapeDtypeStructs of ``linalg._cg_state0``'s tuple, with the
    sharded-vector layout ``dist_cg`` solves over — so the body lowers
    as the SPMD program the solver while_loop runs."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from legate_sparse_tpu.parallel.mesh import COL_AXIS, ROW_AXIS
    from legate_sparse_tpu.types import index_dtype

    spec = (P((ROW_AXIS, COL_AXIS)) if dA.grid is not None
            else P(ROW_AXIS))
    sh = NamedSharding(dA.mesh, spec)
    vec = lambda: jax.ShapeDtypeStruct(                 # noqa: E731
        (dA.rows_padded,), np.float32, sharding=sh)
    scal = lambda dt: jax.ShapeDtypeStruct((), dt)      # noqa: E731
    idx = index_dtype()
    return (vec(), vec(), vec(), scal(np.float32), scal(idx),
            scal(np.bool_), scal(np.float32), scal(idx))


def _cg_body_build(fixture_key: str, **shard_kwargs):
    import jax

    from legate_sparse_tpu.linalg import _cg_builders
    from legate_sparse_tpu.obs import comm as _comm

    dA = _dist_A(fixture_key, **shard_kwargs)
    _cond, body = _cg_builders(dA.matvec_fn(), lambda r: r,
                               CG_CONV_TEST_ITERS)
    state = _cg_state_specs(dA)
    hlo = jax.jit(body).lower(state).as_text()
    jaxpr = jax.make_jaxpr(body)(state)
    # The body's three scalar vdots (rho, pq, ||r||^2) psum at COMPILE
    # time (partitioner-inserted): modeled, deferred, not in the IR.
    deferred = {"psum": 3 * _comm.psum_bytes(1, 4, dA.num_shards)}
    return Built(hlo=hlo, jaxpr=jaxpr, predicted=_spmv_predicted(dA),
                 deferred=deferred,
                 notes={"conv_test_iters": CG_CONV_TEST_ITERS})


_program("dist/cg/1d-row/halo/f32", "dist", _SOLVER_SRC)(
    lambda: _cg_body_build("dA_halo"))
_program("dist/cg/2d-block/panel/f32", "dist", _SOLVER_SRC)(
    lambda: _cg_body_build("dA_2d", layout="2d-block"))


@_program("dist/gmres/1d-row/halo/f32", "dist", _SOLVER_SRC)
def _build_gmres_cycle():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from legate_sparse_tpu.linalg import _gmres_cycle
    from legate_sparse_tpu.parallel.mesh import ROW_AXIS

    dA = _dist_A("dA_halo")
    sh = NamedSharding(dA.mesh, P(ROW_AXIS))
    vec = jax.ShapeDtypeStruct((dA.rows_padded,), np.float32,
                               sharding=sh)
    A_mv, M_mv = dA.matvec_fn(), lambda r: r
    fn = lambda x, b: _gmres_cycle(A_mv, M_mv, x, b,   # noqa: E731
                                   GMRES_RESTART)
    hlo = jax.jit(fn).lower(vec, vec).as_text()
    jaxpr = jax.make_jaxpr(fn)(vec, vec)
    # The Arnoldi fori_loop body traces ONCE but runs restart times:
    # per-dispatch byte totals are not a lower-time quantity, so the
    # bytes rule is scoped out (predicted=None) while the schedule,
    # transfer and dtype contracts still bind.
    return Built(hlo=hlo, jaxpr=jaxpr, predicted=None,
                 notes={"restart": GMRES_RESTART, "loops": True})


# ------------------------------------------------------------------ #
# dist_spgemm phase-1 (product count) programs
# ------------------------------------------------------------------ #

@_program("dist/spgemm/1d-row/all-gather/f32", "dist", _SPGEMM_SRC)
def _build_spgemm_1d():
    import jax
    import jax.numpy as jnp

    from legate_sparse_tpu.obs import comm as _comm
    from legate_sparse_tpu.parallel.dist_spgemm import (
        _esc_t_fn, _layout_of,
    )

    dA = _dist_A("dA_spgemm", force_all_gather=True)
    la = lb = _layout_of(dA)
    R = dA.num_shards
    placeholder = jnp.zeros((R, 1), dtype=jnp.int32)

    def arrays_of(M):
        return (
            M.data, M.cols,
            M.counts if M.counts is not None else placeholder,
            M.row_ids if M.row_ids is not None else placeholder,
            M.gather_globals if M.gather_globals is not None
            else placeholder,
        )

    args = arrays_of(dA) + arrays_of(dA)
    fn = _esc_t_fn(dA.mesh, la, lb, None)
    hlo = fn.lower(*args).as_text()
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    # Phase 1 all_gathers B's structural arrays (counts; plus row_ids
    # for non-ELL layouts) along the row axis — price each gathered
    # block by its per-shard element count.
    gathered = [arrays_of(dA)[2]] if lb.ell else [
        arrays_of(dA)[2], arrays_of(dA)[3]]
    ag = sum(
        _comm.all_gather_bytes(
            int(a.size) // R, a.dtype.itemsize, R)
        for a in gathered)
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={"all_gather": ag},
                 notes={"ell": bool(lb.ell), "phase": "count"})


@_program("dist/spgemm/2d-block/panel/f32", "dist", _SPGEMM_SRC)
def _build_spgemm_2d():
    import jax

    from legate_sparse_tpu.obs import comm as _comm
    from legate_sparse_tpu.parallel.dist_spgemm import _esc2d_t_fn

    dA = _dist_A("dA_2d", layout="2d-block")
    Rr, Rc = dA.grid
    fn = _esc2d_t_fn(dA.mesh, dA.cols_per_shard, dA.rows_per_shard)
    args = (dA.cols, dA.counts, dA.row_ids, dA.counts)
    hlo = fn.lower(*args).as_text()
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    # Four structural gathers: A cols + counts along mesh cols (Rr
    # groups of Rc), B row_ids + counts along mesh rows (Rc groups of
    # Rr) — the SUMMA phase-1 terms of obs/comm, op by op.
    capA = int(dA.cols.shape[-1])
    capB = int(dA.row_ids.shape[-1])
    ag = (
        Rr * _comm.all_gather_bytes(capA, dA.cols.dtype.itemsize, Rc)
        + Rr * _comm.all_gather_bytes(1, dA.counts.dtype.itemsize, Rc)
        + Rc * _comm.all_gather_bytes(capB,
                                      dA.row_ids.dtype.itemsize, Rr)
        + Rc * _comm.all_gather_bytes(1, dA.counts.dtype.itemsize, Rr)
    )
    return Built(hlo=hlo, jaxpr=jaxpr, predicted={"all_gather": ag},
                 notes={"grid": [Rr, Rc], "phase": "count"})
