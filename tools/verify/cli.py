# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""planverify CLI (see ``tools/planverify.py`` for the entry shim,
which pins the virtual CPU mesh before jax initializes).

Exit codes match sparselint: 0 = no active findings; 1 = findings;
2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

from . import catalog, rules
from .runner import (
    DEFAULT_BASELINE, run_verify, select_programs, update_contracts,
    write_baseline,
)


def changed_files(repo: str):
    """Repo-relative paths touched vs HEAD (unstaged + staged +
    untracked) — same selection as sparselint --changed."""
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others",
                  "--exclude-standard"]):
        try:
            text = subprocess.run(
                args, cwd=repo, capture_output=True, text=True,
                check=True).stdout
        except (OSError, subprocess.CalledProcessError) as e:
            raise RuntimeError(f"--changed needs git: {e}") from e
        out.update(l.strip() for l in text.splitlines() if l.strip())
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="planverify",
        description="StableHLO/jaxpr contract verifier for compiled "
                    "kernels and dist plans: lowers every registered "
                    "program (never executes) and checks collective "
                    "schedule, comm bytes, transfer freedom and dtype "
                    "discipline against committed contracts "
                    "(docs/VERIFY.md).")
    ap.add_argument("programs", nargs="*",
                    help="program ids to verify (default: the full "
                         "catalog; see --list-programs)")
    ap.add_argument("--changed", action="store_true",
                    help="verify only programs whose source modules "
                         "or contract files differ from git HEAD")
    ap.add_argument("--rules",
                    help="comma-separated rule ids to run (default: "
                         "all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings artifact on "
                         "stdout (tools/doctor.py ingests this)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: "
                         "tools/verify/baseline.json); 'none' "
                         "disables")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current "
                         "active findings and exit 0")
    ap.add_argument("--update-contracts", action="store_true",
                    help="regenerate the committed contract files "
                         "from the current lowered IR (requires "
                         "--reason)")
    ap.add_argument("--reason",
                    help="justification committed into regenerated "
                         "contracts (required with "
                         "--update-contracts)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--list-programs", action="store_true",
                    help="print the program catalog and exit")
    args = ap.parse_args(argv)

    registry = rules.all_rules()
    if args.list_rules:
        width = max(len(r) for r in registry)
        for rid in sorted(registry):
            print(f"{rid.ljust(width)}  {registry[rid].description}")
        return 0
    if args.list_programs:
        for p in catalog.all_programs():
            print(p.pid)
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",")
                    if r.strip()]
        unknown = sorted(set(rule_ids) - set(registry))
        if unknown:
            print(f"planverify: unknown rule(s): "
                  f"{', '.join(unknown)}", file=sys.stderr)
            return 2

    import os

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        if args.changed:
            progs = select_programs(selection=changed_files(repo))
        elif args.programs:
            progs = select_programs(program_ids=args.programs)
        else:
            progs = select_programs()
    except (RuntimeError, KeyError) as e:
        print(f"planverify: {e}", file=sys.stderr)
        return 2

    if args.update_contracts:
        if not args.reason or not args.reason.strip():
            print("planverify: --update-contracts requires a "
                  "non-empty --reason", file=sys.stderr)
            return 2
        paths = update_contracts(args.reason, programs=progs)
        for p in paths:
            print(f"planverify: wrote {os.path.relpath(p, repo)}")
        return 0

    if not progs:
        print("planverify: nothing to verify for this selection")
        return 0

    baseline = None if args.baseline == "none" else args.baseline
    if args.update_baseline:
        res = run_verify(programs=progs, rule_ids=rule_ids,
                         baseline_path=None)
        write_baseline(baseline or DEFAULT_BASELINE, res.active)
        print(f"planverify: baseline rewritten with "
              f"{len(res.active)} entry(ies) -> "
              f"{baseline or DEFAULT_BASELINE}")
        return 0

    res = run_verify(programs=progs, rule_ids=rule_ids,
                     baseline_path=baseline)

    if args.as_json:
        print(json.dumps(res.to_json(), indent=1, sort_keys=True))
        return res.exit_code

    for f in res.active:
        print(f.render())
    for key in res.stale_baseline:
        print(f"planverify: stale baseline entry {key!r} matched "
              f"nothing — remove it", file=sys.stderr)
    n_base = len(res.baselined)
    extra = f" ({n_base} baselined)" if n_base else ""
    if res.active:
        print(f"planverify: FAILED — {len(res.active)} finding(s) "
              f"across {len(res.rules_run)} rule(s), "
              f"{len(res.programs_checked)} program(s){extra}",
              file=sys.stderr)
        return 1
    print(f"planverify: OK — 0 findings across "
          f"{len(res.rules_run)} rule(s), "
          f"{len(res.programs_checked)} program(s){extra}")
    return 0
