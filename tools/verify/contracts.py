# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Contract storage for planverify — deliberately jax-free.

A *contract* is one committed JSON file per verified program under
``tools/verify/contracts/``, recording the collective schedule, byte
volumes, custom-call allowlist, transfer-freedom bit and dtype
allowances the lowered IR exhibited when the contract was last
(re)generated with ``--update-contracts --reason "..."``.  Program ids
are hierarchical (``dist/spmv/1d-row/halo/f32``); filenames are the
mechanical kebab-case flattening so the sparselint ``plan-contract``
rule can map registry labels and plan-shape triples to expected files
without importing jax (this module is its only planverify import).

Contracts are committed artifacts: no timestamps or machine-local
paths, sorted keys, one canonical rendering — regenerating without an
IR change must produce a byte-identical file.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

CONTRACT_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "contracts")

CONTRACT_VERSION = 1


def slug(part: str) -> str:
    """Kebab-case one program-id path segment (``dist_spmv`` and
    ``dist/spmv`` flatten identically — ids are mechanical)."""
    return part.replace("_", "-").replace("/", "-").lower()


def contract_name(program_id: str) -> str:
    return slug(program_id) + ".json"


def contract_path(program_id: str,
                  contracts_dir: Optional[str] = None) -> str:
    return os.path.join(contracts_dir or CONTRACT_DIR,
                        contract_name(program_id))


def kernel_prefix(label: str) -> str:
    """Expected contract-filename prefix for one autotune registry
    kernel label (``csr-rowids`` -> ``kernel-csr-rowids-``)."""
    return "kernel-" + slug(label) + "-"


def dist_prefix(shape_triple) -> str:
    """Expected contract-filename prefix for one dist plan-shape
    triple (``("dist_spmv", "1d-row", "halo")`` ->
    ``dist-spmv-1d-row-halo``)."""
    op, layout, realization = shape_triple
    return "-".join(slug(p) for p in (op, layout, realization))


def list_contracts(contracts_dir: Optional[str] = None) -> List[str]:
    """Committed contract filenames, sorted."""
    d = contracts_dir or CONTRACT_DIR
    if not os.path.isdir(d):
        return []
    return sorted(f for f in os.listdir(d) if f.endswith(".json"))


def load_contract(program_id: str,
                  contracts_dir: Optional[str] = None
                  ) -> Optional[Dict]:
    p = contract_path(program_id, contracts_dir)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def write_contract(program_id: str, payload: Dict,
                   contracts_dir: Optional[str] = None) -> str:
    d = contracts_dir or CONTRACT_DIR
    os.makedirs(d, exist_ok=True)
    p = contract_path(program_id, contracts_dir)
    if payload.get("version") != CONTRACT_VERSION:
        raise ValueError(
            f"contract payload for {program_id} has version "
            f"{payload.get('version')!r}, expected {CONTRACT_VERSION}")
    with open(p, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return p
