# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""StableHLO-text and jaxpr readers for planverify.

Everything here is a *reader*: pure functions from lowered-IR text (or
a traced jaxpr object) to small structured summaries the rules compare
against contracts.  No jax import at module level — the jaxpr walkers
duck-type on ``.eqns``/``.jaxpr`` so this module stays importable by
jax-free callers (the sparselint plan-contract rule imports the
package's contract helpers, which must not drag a backend in).

StableHLO syntax assumptions (validated against jax 0.4.x CPU
lowerings of shard_map programs — see tests/test_verify.py, which
re-validates them on every run so a jax upgrade that changes the
printing breaks loudly here, not silently in a contract):

- collectives print in the quoted generic form::

    %2 = "stablehlo.collective_permute"(%0) <{channel_handle = ...,
        source_target_pairs = dense<[[0, 1], [1, 2]]> :
        tensor<8x2xi64>}> : (tensor<1xf32>) -> tensor<1xf32>

- ``all_gather``/``all_reduce``/``reduce_scatter``/``all_to_all``
  carry ``replica_groups = dense<...> : tensor<GxSxi64>`` (G groups of
  S participants); ``reduce_scatter``/``all_reduce`` interpose a
  reduction region ``({ ... })`` before the type signature, so the
  operand type is read *after* the balanced region close.
- host round-trips surface as ``stablehlo.custom_call`` with an
  ``@target`` (pretty form) or ``call_target_name = "..."`` (generic
  form), or as ``stablehlo.infeed``/``outfeed``/``send``/``recv``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

COLLECTIVE_KINDS = (
    "collective_permute", "all_gather", "all_reduce",
    "reduce_scatter", "all_to_all",
)

# IR op name -> the comm-ledger kind obs/comm.py prices.  Both
# all_reduce and reduce_scatter settle into the ledger's "psum" bucket:
# the model prices the *reduction*, the partitioner picks the op.
# Non-add reductions (the semiring dist programs — docs/GRAPH.md) are
# priced under their own ledger kinds; ``CollectiveOp.model_kind``
# refines an add-less all_reduce via ``_REDUCE_MODEL_KIND``.
MODEL_KIND = {
    "collective_permute": "ppermute",
    "all_gather": "all_gather",
    "all_reduce": "psum",
    "reduce_scatter": "psum",
    "all_to_all": "all_to_all",
}

# Reduction-region op -> ledger kind for non-add all_reduce.  "or" is
# how a boolean max may print; a max over i1 *operands* is classified
# as "or" at parse time (jax.lax.pmax over bool lowers to
# ``stablehlo.maximum : tensor<i1>`` — the ledger prices it as "por").
_REDUCE_MODEL_KIND = {"min": "pmin", "max": "pmax", "or": "por"}


def ledger_kind(kind: str, reduce: Optional[str] = None) -> str:
    """Comm-ledger kind for one lowered collective: ``MODEL_KIND``
    refined by the reduction-region op when an ``all_reduce`` computes
    something other than add (the semiring dist programs)."""
    if kind == "all_reduce" and reduce in _REDUCE_MODEL_KIND:
        return _REDUCE_MODEL_KIND[reduce]
    return MODEL_KIND[kind]

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "complex<f32>": 8, "complex<f64>": 16,
}

# Float widths for the widening check (HLO side).
_FLOAT_WIDTH = {"f8E4M3FN": 1, "f8E5M2": 1, "bf16": 2, "f16": 2,
                "f32": 4, "f64": 8}

_TENSOR_RE = re.compile(r"tensor<((?:\d+x)*)([^>]+(?:<[^>]*>)?)>")
_GROUPS_RE = re.compile(
    r"replica_groups\s*=\s*dense<.*?>\s*:\s*tensor<(\d+)x(\d+)xi64>",
    re.S)
_PAIRS_SHAPE_RE = re.compile(
    r"source_target_pairs\s*=\s*dense<(.*?)>\s*:\s*tensor<(\d+)x2xi64>",
    re.S)
_PAIR_RE = re.compile(r"\[\s*(-?\d+)\s*,\s*(-?\d+)\s*\]")
_SIG_RE = re.compile(r":\s*\(([^)]*)\)\s*->")
_CUSTOM_CALL_AT_RE = re.compile(
    r"stablehlo\.custom_call\s*@([A-Za-z_][\w.\-]*)")
_CUSTOM_CALL_NAME_RE = re.compile(r'call_target_name\s*=\s*"([^"]+)"')
_CONVERT_RE = re.compile(
    r"stablehlo\.convert[^\n]*:\s*\(tensor<((?:\d+x)*)(\w+)>\)\s*->\s*"
    r"tensor<(?:\d+x)*(\w+)>")
_INOUT_FEED_RE = re.compile(
    r'"?stablehlo\.(infeed|outfeed|send|recv)"?[ ("]')


def tensor_bytes(type_str: str) -> int:
    """Byte size of one ``tensor<...>`` type (scalar tensors = one
    element)."""
    m = _TENSOR_RE.search(type_str)
    if not m:
        raise ValueError(f"not a tensor type: {type_str!r}")
    dims, dtype = m.group(1), m.group(2).strip()
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    try:
        return n * _DTYPE_BYTES[dtype]
    except KeyError:
        raise ValueError(f"unknown element type {dtype!r} in "
                         f"{type_str!r}") from None


@dataclass(frozen=True)
class CollectiveOp:
    """One lowered collective, in program order."""

    kind: str                 # IR op name (COLLECTIVE_KINDS)
    operand_bytes: int        # first-operand payload size
    n_pairs: int = 0          # collective_permute: total pairs
    moved_pairs: int = 0      # collective_permute: non-self pairs
    # replica_groups shape (n_groups, group_size); None for permutes.
    groups: Optional[Tuple[int, int]] = None
    # Reduction-region op ("add"/"min"/"max"/"or") for all_reduce /
    # reduce_scatter; None for region-less collectives.
    reduce: Optional[str] = None

    @property
    def model_kind(self) -> str:
        return ledger_kind(self.kind, self.reduce)

    def signature(self) -> dict:
        """JSON-stable schedule entry (what contracts commit)."""
        sig = {
            "kind": self.kind,
            "operand_bytes": self.operand_bytes,
            "moved_pairs": self.moved_pairs if
            self.kind == "collective_permute" else None,
            "groups": list(self.groups) if self.groups else None,
        }
        # Only the non-add reductions stamp the schedule entry, so
        # every contract committed before the semiring programs stays
        # byte-identical (add is the implied default).
        if self.reduce in _REDUCE_MODEL_KIND:
            sig["reduce"] = self.reduce
        return sig


def _region_end(text: str, start: int) -> int:
    """Index just past the balanced ``{...}`` region opening at
    ``text[start]`` (which must be '{')."""
    depth = 0
    for i in range(start, len(text)):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    raise ValueError("unbalanced region in StableHLO text")


_REDUCE_OP_RE = re.compile(
    r"stablehlo\.(add|minimum|maximum|or|and|multiply)\b")


def _classify_reduce(region: str) -> Optional[str]:
    """Reduce-op tag ("add"/"min"/"max"/"or"/...) of one reduction
    region's text.  A ``maximum`` over ``i1`` operands is boolean or
    (how ``jax.lax.pmax`` over a bool frontier prints), so it
    classifies as "or" — the ledger kind the semiring programs price
    it under ("por")."""
    m = _REDUCE_OP_RE.search(region)
    if m is None:
        return None
    op = {"minimum": "min", "maximum": "max"}.get(m.group(1), m.group(1))
    if op == "max" and re.search(r"tensor<i1>", region):
        return "or"
    return op


def parse_collectives(text: str) -> List[CollectiveOp]:
    """All collective ops in ``text``, in textual (= program) order."""
    ops: List[CollectiveOp] = []
    for m in re.finditer(
            r'"stablehlo\.(%s)"' % "|".join(COLLECTIVE_KINDS), text):
        kind = m.group(1)
        # Attribute block <{...}> directly after the operand list.
        am = re.compile(r"<\{(.*?)\}>", re.S).search(text, m.end())
        if am is None:
            raise ValueError(f"collective {kind} without attributes "
                             f"near offset {m.start()}")
        attrs = am.group(1)
        pos = am.end()
        # Read an optional reduction region "({ ... })" before the
        # type signature (all_reduce / reduce_scatter) — both to skip
        # past it and to classify the reduce op it computes.
        reduce = None
        rm = re.compile(r"\s*\(\s*\{").match(text, pos)
        if rm:
            rstart = text.index("{", pos)
            pos = _region_end(text, rstart)
            reduce = _classify_reduce(text[rstart:pos])
            # past the region's closing ')'
            pos = text.index(")", pos) + 1
        sm = _SIG_RE.search(text, pos)
        if sm is None:
            raise ValueError(f"collective {kind} without a type "
                             f"signature near offset {m.start()}")
        first_operand = sm.group(1).split(",")[0]
        ob = tensor_bytes(first_operand)

        n_pairs = moved = 0
        groups = None
        pm = _PAIRS_SHAPE_RE.search(attrs)
        if pm:
            n_pairs = int(pm.group(2))
            pairs = _PAIR_RE.findall(pm.group(1))
            if pairs:
                moved = sum(1 for s, t in pairs if s != t)
            # splat form dense<v> means every pair is (v, v): moved 0
        gm = _GROUPS_RE.search(attrs)
        if gm:
            groups = (int(gm.group(1)), int(gm.group(2)))
        ops.append(CollectiveOp(kind=kind, operand_bytes=ob,
                                n_pairs=n_pairs, moved_pairs=moved,
                                groups=groups, reduce=reduce))
    return ops


def parse_custom_calls(text: str) -> List[str]:
    """Custom-call targets in textual order (pretty ``@target`` and
    generic ``call_target_name`` forms)."""
    hits = [(m.start(), m.group(1))
            for m in _CUSTOM_CALL_AT_RE.finditer(text)]
    hits += [(m.start(), m.group(1))
             for m in _CUSTOM_CALL_NAME_RE.finditer(text)]
    return [t for _, t in sorted(hits)]


def parse_feeds(text: str) -> List[str]:
    """infeed/outfeed/send/recv op names present in the text."""
    return sorted({m.group(1) for m in _INOUT_FEED_RE.finditer(text)})


def hlo_widening_converts(text: str) -> List[str]:
    """``"src->dst"`` strings for every float-widening
    ``stablehlo.convert`` in the text."""
    out = []
    for m in _CONVERT_RE.finditer(text):
        src, dst = m.group(2), m.group(3)
        if (src in _FLOAT_WIDTH and dst in _FLOAT_WIDTH
                and _FLOAT_WIDTH[dst] > _FLOAT_WIDTH[src]):
            out.append(f"{src}->{dst}")
    return out


# ------------------------------------------------------------------ #
# jaxpr walking (duck-typed: no jax import)
# ------------------------------------------------------------------ #

# Primitives that round-trip through the host.  ``debug_callback`` is
# included deliberately: a debug print inside a solver loop body is a
# per-iteration host sync on real hardware.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed",
})

# Loop-carrying primitives: a callback under one of these runs every
# iteration, the worst case the transfer rule calls out specially.
LOOP_PRIMS = frozenset({"while", "scan"})


def _param_jaxprs(value: Any) -> Iterator[Any]:
    """Yield jaxpr-like objects inside one eqn param value."""
    if hasattr(value, "eqns"):
        yield value
    elif hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        yield value.jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _param_jaxprs(v)


def iter_eqns(jaxpr: Any, ancestors: Tuple[str, ...] = ()
              ) -> Iterator[Tuple[Any, Tuple[str, ...]]]:
    """Depth-first ``(eqn, ancestor-primitive-names)`` over a (closed)
    jaxpr, recursing into while/scan/cond/pjit/shard_map bodies."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn, ancestors
        for v in eqn.params.values():
            for sub in _param_jaxprs(v):
                yield from iter_eqns(
                    sub, ancestors + (eqn.primitive.name,))


def host_callbacks(jaxpr: Any) -> List[Tuple[str, bool]]:
    """``(primitive, inside_loop_body)`` for every host-round-trip
    primitive anywhere in the jaxpr."""
    out = []
    for eqn, anc in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMS:
            out.append((name, any(a in LOOP_PRIMS for a in anc)))
    return out


_SHORT = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "complex64": "c64", "complex128": "c128",
    "float8_e4m3fn": "f8E4M3FN", "float8_e5m2": "f8E5M2",
}


def _short(dtype: Any) -> str:
    name = getattr(dtype, "name", str(dtype))
    return _SHORT.get(name, name)


def jaxpr_widening_converts(jaxpr: Any) -> List[Tuple[str, bool]]:
    """``("src->dst", inside_loop_body)`` for every float-widening
    ``convert_element_type`` in the jaxpr (ints/bools are exempt —
    dtype discipline is about silent precision inflation of values,
    not index bookkeeping)."""
    import numpy as np

    # jax is necessarily importable here (the caller holds a jaxpr);
    # its dtype lattice knows the ml_dtypes floats (bf16/f8*) whose
    # raw numpy kind is 'V', not 'f'.
    from jax.dtypes import issubdtype as _issub

    def _floatish(dt):
        return _issub(dt, np.floating) or _issub(dt, np.complexfloating)

    out = []
    for eqn, anc in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = np.dtype(eqn.invars[0].aval.dtype)
        dst = np.dtype(eqn.params["new_dtype"])
        if (_floatish(src) and _floatish(dst)
                and dst.itemsize > src.itemsize):
            out.append((f"{_short(src)}->{_short(dst)}",
                        any(a in LOOP_PRIMS for a in anc)))
    return out
