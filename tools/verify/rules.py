# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""planverify rules: the four lowered-IR contracts.

Mirrors the sparselint rule shape (stable kebab-case id, severity,
one-line description, registry via ``@register``, mandatory
falsifiability drill) but checks *programs* instead of source files:
``check(program, built, contract)`` yields ``Finding``s rendered
``path:line: severity: [rule-id] message`` with ``path`` = the
program's contract file (schedule/bytes drift) or its primary source
module (IR-intrinsic violations), and ``line`` 0 — a lowered program
has no meaningful line numbers, and the line-free position keeps
baseline keys stable (tools/common/findings.py).

Every rule must be falsifiable: ``falsifiability()`` lowers a small
known-bad synthetic program (an extra psum, a host callback inside a
while body, a silent bf16->f32 widen) and must produce at least one
finding — drilled by tests/test_verify.py, same discipline as
tools/lint.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from ..common.findings import Finding
from . import hlo
from .catalog import Built, Program
from .contracts import contract_name

# Partitioning bookkeeping custom_calls jax emits for sharded
# programs: annotations, not host transfers.
ALLOWED_CUSTOM_CALLS = frozenset({
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
})

_UPDATE_HINT = ("run `python tools/planverify.py --update-contracts "
                "--reason '...'` if the new program is intended")


def finding_path(program: Program, contract_side: bool) -> str:
    if contract_side:
        return "tools/verify/contracts/" + contract_name(program.pid)
    return program.sources[0]


def schedule_of(built: Built) -> List[dict]:
    """Contract-shaped schedule entries (signature + per-op bytes in
    ledger convention) from the lowered text."""
    from legate_sparse_tpu.obs import comm as _comm

    out = []
    for op in hlo.parse_collectives(built.hlo):
        sig = op.signature()
        n_groups, size = op.groups if op.groups else (0, 0)
        sig["bytes"] = _comm.lowered_op_bytes(
            op.kind, op.operand_bytes,
            group_sizes=(size,) * n_groups,
            moved_pairs=op.moved_pairs)
        out.append(sig)
    return out


def lowered_volumes(built: Built) -> Dict[str, int]:
    """Per-ledger-kind byte totals of the explicitly lowered
    collectives."""
    vols: Dict[str, int] = {}
    for entry in schedule_of(built):
        kind = hlo.ledger_kind(entry["kind"], entry.get("reduce"))
        vols[kind] = vols.get(kind, 0) + entry["bytes"]
    return {k: v for k, v in vols.items() if v > 0}


def transfer_violations(built: Built) -> List[Tuple[str, str]]:
    """(kind, detail) pairs for every host-transfer site in the
    program, from both the StableHLO text and the jaxpr."""
    out: List[Tuple[str, str]] = []
    for feed in hlo.parse_feeds(built.hlo):
        out.append(("feed", f"stablehlo.{feed} op in lowered IR"))
    for target in hlo.parse_custom_calls(built.hlo):
        if target not in ALLOWED_CUSTOM_CALLS:
            out.append(("custom_call",
                        f"non-partitioning custom_call @{target}"))
    if built.jaxpr is not None:
        for prim, in_loop in hlo.host_callbacks(built.jaxpr):
            where = (" inside a while/scan loop body (per-iteration "
                     "host sync)" if in_loop else "")
            out.append(("callback",
                        f"host callback primitive '{prim}'{where}"))
    return out


def contract_payload(program: Program, built: Built,
                     reason: str) -> dict:
    """The committed-contract JSON for one built program — written by
    ``--update-contracts``, compared by the rules.  Deterministic:
    same IR in, byte-identical file out."""
    sched = schedule_of(built)
    return {
        "version": 1,
        "program": program.pid,
        "reason": reason,
        "schedule": sched,
        "lowered_volumes": lowered_volumes(built),
        "predicted_volumes": built.predicted,
        "deferred_volumes": built.deferred,
        "custom_calls": sorted(set(hlo.parse_custom_calls(built.hlo))),
        "transfer_free": not transfer_violations(built),
        "widening_allowed": sorted(built.widening_allowed),
        "notes": built.notes,
    }


class VerifyRule:
    """Base class; subclasses register with ``@register``."""

    id: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, program: Program, built: Built,
              contract: Optional[dict]) -> Iterable[Finding]:
        raise NotImplementedError

    def falsifiability(self) -> List[Finding]:
        """Findings on a seeded known-bad lowered program.  Must be
        non-empty — drilled by tests/test_verify.py."""
        raise NotImplementedError

    def _finding(self, program: Program, message: str,
                 contract_side: bool = True) -> Finding:
        return Finding(rule=self.id,
                       path=finding_path(program, contract_side),
                       line=0, message=message,
                       severity=self.severity)


_RULES: Dict[str, VerifyRule] = {}


def register(cls):
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule class {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> Dict[str, VerifyRule]:
    return dict(_RULES)


def get_rule(rule_id: str) -> VerifyRule:
    return _RULES[rule_id]


def _sig_only(entry: dict) -> tuple:
    """Schedule identity the schedule rule compares: kind + operand
    size + topology (bytes equality is the comm-bytes rule's job,
    split so a finding names the invariant that actually broke)."""
    return (entry["kind"], entry["operand_bytes"],
            entry.get("moved_pairs"),
            tuple(entry["groups"]) if entry.get("groups") else None)


@register
class CollectiveScheduleRule(VerifyRule):
    id = "collective-schedule"
    description = ("lowered collective kind/count/topology/ordering "
                   "must match the committed contract")

    def check(self, program, built, contract):
        if contract is None:
            yield self._finding(
                program,
                f"{program.pid}: no committed contract — "
                f"{_UPDATE_HINT}")
            return
        got = [_sig_only(e) for e in schedule_of(built)]
        want = [_sig_only(e) for e in contract.get("schedule", [])]
        if got == want:
            return
        detail = (f"lowered {len(got)} collective(s), contract has "
                  f"{len(want)}")
        for i, (g, w) in enumerate(zip(got, want)):
            if g != w:
                detail = (f"op {i} diverges: lowered "
                          f"{g[0]}(operand={g[1]}B) vs contract "
                          f"{w[0]}(operand={w[1]}B)")
                break
        else:
            if len(got) > len(want):
                detail += f"; first extra lowered op: {got[len(want)][0]}"
            elif len(want) > len(got):
                detail += f"; first missing op: {want[len(got)][0]}"
        yield self._finding(
            program,
            f"{program.pid}: collective schedule drifted from "
            f"contract ({detail}) — {_UPDATE_HINT}")


@register
class CommBytesRule(VerifyRule):
    id = "comm-bytes"
    description = ("per-collective IR operand bytes must equal the "
                   "obs/comm model prediction and the contract, "
                   "exactly")

    def check(self, program, built, contract):
        vols = lowered_volumes(built)
        if built.predicted is not None:
            kinds = sorted(set(vols) | set(built.predicted))
            for kind in kinds:
                got = vols.get(kind, 0)
                want = built.predicted.get(kind, 0)
                if got != want:
                    yield self._finding(
                        program,
                        f"{program.pid}: lowered {kind} moves {got} "
                        f"bytes but obs/comm prices {want} — model "
                        f"and program disagree", contract_side=False)
        if contract is None:
            return
        if vols != contract.get("lowered_volumes", {}):
            yield self._finding(
                program,
                f"{program.pid}: lowered byte volumes {vols} != "
                f"contracted {contract.get('lowered_volumes')} — "
                f"{_UPDATE_HINT}")
        if built.predicted != contract.get("predicted_volumes"):
            yield self._finding(
                program,
                f"{program.pid}: obs/comm prediction "
                f"{built.predicted} != contracted "
                f"{contract.get('predicted_volumes')} (model "
                f"drifted?) — {_UPDATE_HINT}")
        if built.deferred != contract.get("deferred_volumes", {}):
            yield self._finding(
                program,
                f"{program.pid}: deferred (partitioner-inserted) "
                f"volumes {built.deferred} != contracted "
                f"{contract.get('deferred_volumes')} — {_UPDATE_HINT}")


@register
class TransferFreedomRule(VerifyRule):
    id = "transfer-freedom"
    description = ("no host callbacks/infeed/outfeed or "
                   "non-partitioning custom_calls in contracted "
                   "programs (solver cycle bodies especially)")

    def check(self, program, built, contract):
        for _kind, detail in transfer_violations(built):
            yield self._finding(
                program, f"{program.pid}: {detail}",
                contract_side=False)


@register
class DtypeDisciplineRule(VerifyRule):
    id = "dtype-discipline"
    description = ("no float-widening converts (bf16->f32, f32->f64) "
                   "beyond the program's declared accumulators")

    def check(self, program, built, contract):
        allowed = set(built.widening_allowed)
        if contract:
            allowed.update(contract.get("widening_allowed", []))
        seen = set()
        if built.jaxpr is not None:
            convs = hlo.jaxpr_widening_converts(built.jaxpr)
        else:
            convs = [(c, False)
                     for c in hlo.hlo_widening_converts(built.hlo)]
        for conv, in_loop in convs:
            if conv in allowed or conv in seen:
                continue
            seen.add(conv)
            where = " inside a loop body" if in_loop else ""
            yield self._finding(
                program,
                f"{program.pid}: undeclared float widening {conv}"
                f"{where} — declare it in widening_allowed if it is "
                f"an intended accumulator", contract_side=False)


# ------------------------------------------------------------------ #
# falsifiability fixtures: small known-bad programs, lowered the same
# way the catalog lowers real ones
# ------------------------------------------------------------------ #

_PROBE = Program(pid="zz-verify-falsifiability-probe", kind="dist",
                 sources=("tools/verify/rules.py",))


def _probe_mesh():
    from .catalog import _row_mesh

    return _row_mesh()


def _psum_built(elems_per_shard: int = 1) -> Built:
    """A one-psum shard_map program over the row mesh."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from legate_sparse_tpu.parallel._compat import shard_map
    from legate_sparse_tpu.parallel.mesh import ROW_AXIS

    mesh = _probe_mesh()
    R = mesh.shape[ROW_AXIS]

    def f(a):
        return jax.lax.psum(a, ROW_AXIS)

    sm = shard_map(f, mesh=mesh, in_specs=P(ROW_AXIS),
                   out_specs=P(None), check_vma=False)
    x = jax.ShapeDtypeStruct(
        (R * elems_per_shard,), np.float32,
        sharding=NamedSharding(mesh, P(ROW_AXIS)))
    return Built(hlo=jax.jit(sm).lower(x).as_text(),
                 jaxpr=jax.make_jaxpr(sm)(x), predicted=None)


def _schedule_falsifiability() -> List[Finding]:
    # Inject an extra psum relative to the contract: the contract says
    # "no collectives", the program lowers one.
    built = _psum_built()
    contract = {"version": 1, "schedule": [], "lowered_volumes": {},
                "predicted_volumes": None, "deferred_volumes": {}}
    return list(get_rule("collective-schedule").check(
        _PROBE, built, contract))


def _bytes_falsifiability() -> List[Finding]:
    # Model says one psum of 1 element; the program psums 4 per shard.
    from legate_sparse_tpu.obs import comm as _comm

    built = _psum_built(elems_per_shard=4)
    built.predicted = {"psum": _comm.psum_bytes(
        1, 4, _probe_mesh().shape["rows"])}
    return list(get_rule("comm-bytes").check(_PROBE, built, None))


def _transfer_falsifiability() -> List[Finding]:
    # A debug print inside a while_loop body: exactly the
    # per-iteration host round-trip the rule exists to forbid.
    import jax
    import numpy as np

    def body(c):
        i, x = c
        jax.debug.print("iter {}", i)
        return i + 1, x + 1.0

    def prog(x):
        return jax.lax.while_loop(lambda c: c[0] < 3, body,
                                  (np.int32(0), x))

    spec = jax.ShapeDtypeStruct((4,), np.float32)
    built = Built(hlo=jax.jit(prog).lower(spec).as_text(),
                  jaxpr=jax.make_jaxpr(prog)(spec), predicted=None)
    return list(get_rule("transfer-freedom").check(_PROBE, built,
                                                   None))


def _dtype_falsifiability() -> List[Finding]:
    # Silent bf16 -> f32 widen with no declared accumulator.
    import jax
    import jax.numpy as jnp

    def prog(a):
        return jnp.sum(a.astype(jnp.float32))

    spec = jax.ShapeDtypeStruct((8,), jnp.bfloat16)
    built = Built(hlo=jax.jit(prog).lower(spec).as_text(),
                  jaxpr=jax.make_jaxpr(prog)(spec), predicted=None)
    return list(get_rule("dtype-discipline").check(_PROBE, built,
                                                   None))


CollectiveScheduleRule.falsifiability = (
    lambda self: _schedule_falsifiability())
CommBytesRule.falsifiability = lambda self: _bytes_falsifiability()
TransferFreedomRule.falsifiability = (
    lambda self: _transfer_falsifiability())
DtypeDisciplineRule.falsifiability = (
    lambda self: _dtype_falsifiability())
