# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""planverify runner: build programs, apply rules, classify findings.

Same disposition pipeline as sparselint (tools/lint/core.py) minus
inline suppression — findings attach to lowered programs, not source
lines, so there is no line to annotate; exemptions go through the
committed contract (``widening_allowed``, regenerated schedules) or,
as a last resort, the baseline.  Baseline keys are the shared
line-free ``(rule, path, message)`` triple from tools/common, with
the same stale-entry reporting so grandfathered drift shrinks instead
of rotting.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.findings import (  # noqa: F401  (re-export for CLI)
    Finding, load_baseline, write_baseline,
)
from . import catalog, rules
from .contracts import contract_name, load_contract, write_contract

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")

# Edits under these prefixes re-verify EVERY program: the verifier or
# the shared byte model itself changed.
_GLOBAL_PREFIXES = ("tools/verify/", "tools/common/",
                    "legate_sparse_tpu/obs/comm.py")


@dataclass
class Result:
    """One verify run's outcome, pre-split by disposition."""

    active: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[Tuple[str, str, str]] = field(
        default_factory=list)
    rules_run: List[str] = field(default_factory=list)
    programs_checked: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.active else 0

    def to_json(self) -> dict:
        return {
            "version": 1,
            "tool": "planverify",
            "findings": [asdict(f) for f in self.active],
            "baselined": [asdict(f) for f in self.baselined],
            "stale_baseline": [
                {"rule": r, "path": p, "message": m}
                for (r, p, m) in self.stale_baseline],
            "rules_run": self.rules_run,
            "programs_checked": self.programs_checked,
            "exit_code": self.exit_code,
        }


def select_programs(selection: Optional[Sequence[str]] = None,
                    program_ids: Optional[Sequence[str]] = None
                    ) -> List[catalog.Program]:
    """Catalog programs to verify.  ``selection`` is a changed-file
    list (``--changed``): a program re-verifies when one of its source
    modules, its contract file, or the verifier itself changed."""
    progs = catalog.all_programs()
    if program_ids is not None:
        wanted = set(program_ids)
        progs = [p for p in progs if p.pid in wanted]
        missing = wanted - {p.pid for p in progs}
        if missing:
            raise KeyError(
                f"unknown program id(s): {', '.join(sorted(missing))}")
    if selection is None:
        return progs
    sel = {s.replace(os.sep, "/") for s in selection}
    if any(s.startswith(_GLOBAL_PREFIXES) for s in sel):
        return progs
    out = []
    for p in progs:
        cpath = "tools/verify/contracts/" + contract_name(p.pid)
        if cpath in sel or any(s in p.sources for s in sel):
            out.append(p)
    return out


def run_verify(programs: Optional[Sequence[catalog.Program]] = None,
               rule_ids: Optional[Sequence[str]] = None,
               baseline_path: Optional[str] = DEFAULT_BASELINE,
               contracts_dir: Optional[str] = None) -> Result:
    """Lower every selected program and run the rule set.

    ``baseline_path=None`` disables baselining; ``contracts_dir``
    overrides the committed contract directory (tests)."""
    registry = rules.all_rules()
    rule_list = ([registry[r] for r in rule_ids] if rule_ids
                 else [registry[k] for k in sorted(registry)])
    progs = (list(programs) if programs is not None
             else catalog.all_programs())

    res = Result(rules_run=[r.id for r in rule_list])
    baseline = load_baseline(baseline_path) if baseline_path else {}
    consumed: Dict[Tuple[str, str, str], int] = {}

    for prog in progs:
        res.programs_checked.append(prog.pid)
        built = catalog.build(prog.pid)
        contract = load_contract(prog.pid, contracts_dir)
        for rule in rule_list:
            for f in sorted(rule.check(prog, built, contract),
                            key=lambda f: (f.path, f.rule, f.message)):
                key = f.baseline_key()
                if baseline.get(key, 0) > consumed.get(key, 0):
                    consumed[key] = consumed.get(key, 0) + 1
                    res.baselined.append(f)
                else:
                    res.active.append(f)

    for key, n in sorted(baseline.items()):
        if consumed.get(key, 0) < n:
            res.stale_baseline.append(key)
    return res


def update_contracts(reason: str,
                     programs: Optional[
                         Sequence[catalog.Program]] = None,
                     contracts_dir: Optional[str] = None) -> List[str]:
    """Regenerate contract files from the current lowered IR.  The
    mandatory ``reason`` is committed into each file — contract churn
    must carry its justification through review."""
    if not reason or not reason.strip():
        raise ValueError("--update-contracts requires a non-empty "
                         "--reason")
    progs = (list(programs) if programs is not None
             else catalog.all_programs())
    paths = []
    for prog in progs:
        built = catalog.build(prog.pid)
        payload = rules.contract_payload(prog, built, reason.strip())
        paths.append(write_contract(prog.pid, payload, contracts_dir))
    return paths
